"""Brownout ladder: hysteresis state machine on a fake clock, the SWR
cache bound, and the class-shedding behavior end-to-end over a real
gateway + model-server pair (stub engine, device-free).

The controller's contract under test: stage s enters only at
burn >= enter*s, leaves only below exit*s, moves at most ONE stage per
evaluate(), any two transitions are dwell-separated, and interactive is
never shed.  Stage 2's stale serves must never outlive TTL + SWR.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from kubernetes_deep_learning_tpu.serving.admission.brownout import (
    BrownoutController,
)
from kubernetes_deep_learning_tpu.serving.cache import ResponseCache
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib


class _FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


class _StubSlo:
    """The one surface BrownoutController reads: enabled + model_windows()."""

    enabled = True

    def __init__(self, burn: float = 0.0, window: str = "5m"):
        self.burn = burn
        self.window = window

    def model_windows(self):
        return {"m": {self.window: {"burn_rate": self.burn}}}


def _controller(burn=0.0, dwell_s=5.0, enter=2.0, exit_=1.0, registry=None):
    slo = _StubSlo(burn)
    clock = _FakeClock()
    ctl = BrownoutController(
        slo, registry=registry, enabled=True, burn_enter=enter,
        burn_exit=exit_, dwell_s=dwell_s, clock=clock,
    )
    return ctl, slo, clock


# --- hysteresis state machine on a fake clock ------------------------------


def test_disabled_without_slo_engine():
    ctl = BrownoutController(None, enabled=True, clock=_FakeClock())
    assert not ctl.enabled
    assert ctl.evaluate() == 0

    class Dead:
        enabled = False

    ctl = BrownoutController(Dead(), enabled=True, clock=_FakeClock())
    assert not ctl.enabled and ctl.max_burn() == 0.0


def test_monotone_walk_up_and_down_every_boundary():
    reg = metrics_lib.Registry()
    ctl, slo, clock = _controller(burn=10.0, dwell_s=5.0, registry=reg)
    # Burn 10 clears every enter boundary (2/4/6/8) at once, yet the
    # ladder climbs exactly one stage per dwell-separated evaluation.
    stages = []
    for _ in range(6):
        clock.t += 6.0
        stages.append(ctl.evaluate())
    assert stages == [1, 2, 3, 4, 4, 4]
    # Full recovery: burn 0 is below exit*s for every s -- one stage down
    # per evaluation, never a cliff back to 0.
    slo.burn = 0.0
    down = []
    for _ in range(6):
        clock.t += 6.0
        down.append(ctl.evaluate())
    assert down == [3, 2, 1, 0, 0, 0]
    # The centrally-minted series agree: gauge back at 0, each boundary
    # crossed exactly once in each direction, no flap pairs beyond that.
    text = reg.render()
    assert "kdlt_brownout_stage 0" in text
    for s in (1, 2, 3, 4):
        for d in ("up", "down"):
            assert (
                f'kdlt_brownout_transitions_total{{stage="{s}",'
                f'direction="{d}"}} 1'
            ) in text


def test_dwell_separates_transitions():
    ctl, slo, clock = _controller(burn=100.0, dwell_s=10.0)
    # The FIRST transition needs no prior dwell (an incident should not
    # wait out a timer that never started).
    clock.t = 0.5
    assert ctl.evaluate() == 1
    # Repeated evaluations inside the dwell hold the stage no matter how
    # hard the signal pushes.
    for dt in (1.0, 3.0, 5.0):
        clock.t = 0.5 + dt
        assert ctl.evaluate() == 1
    clock.t = 11.0  # dwell elapsed -> exactly one more step
    assert ctl.evaluate() == 2
    assert ctl.evaluate() == 2  # and immediately re-held


def test_dead_band_holds_stage_without_flapping():
    ctl, slo, clock = _controller(burn=10.0, dwell_s=0.0, enter=2.0, exit_=1.0)
    assert [ctl.evaluate() for _ in range(2)] == [1, 2]
    # Burn in [exit*2, enter*3) = [2, 6): too low to enter 3, too high to
    # leave 2 -- the hysteresis dead band where stage 2 holds steady.
    for burn in (2.0, 3.5, 5.9):
        slo.burn = burn
        for _ in range(5):
            assert ctl.evaluate() == 2
    assert len(ctl.transitions) == 2  # nothing beyond the two climbs


def test_noisy_burn_cannot_flap_faster_than_dwell():
    ctl, slo, clock = _controller(burn=0.0, dwell_s=10.0)
    # A signal oscillating across the stage-1 boundary every second: with
    # a 10 s dwell the ladder may move at most once per 10 s.
    for i in range(100):
        clock.t = float(i)
        slo.burn = 100.0 if i % 2 == 0 else 0.0
        ctl.evaluate()
    times = [tr["t"] for tr in ctl.transitions]
    assert all(b - a >= 10.0 for a, b in zip(times, times[1:])), times
    assert all(abs(tr["to"] - tr["from"]) == 1 for tr in ctl.transitions)


def test_misconfigured_exit_clamps_below_enter():
    # exit >= enter would remove the dead band entirely (a thermostat);
    # the controller degrades it to enter/2 instead of flapping.
    ctl, _, _ = _controller(enter=2.0, exit_=3.0)
    assert ctl.burn_exit == 1.0
    ctl, _, _ = _controller(enter=2.0, exit_=0.0)
    assert ctl.burn_exit == 1.0


def test_stage_gates_and_shed_classes():
    ctl, slo, clock = _controller(burn=100.0, dwell_s=0.0)
    expect = {
        0: (False, False, set()),
        1: (True, False, set()),
        2: (True, True, set()),
        3: (True, True, {"best-effort"}),
        4: (True, True, {"best-effort", "batch"}),
    }
    for stage in range(5):
        hedge_off, stale, shed = expect[stage]
        assert ctl.stage == stage
        assert ctl.hedging_disabled is hedge_off
        assert ctl.serve_stale is stale
        for cls in ("interactive", "batch", "best-effort"):
            assert ctl.sheds(cls) is (cls in shed), (stage, cls)
        if stage < 4:
            ctl.evaluate()
    # Interactive is never shed, by construction, at any stage.
    assert not ctl.sheds("interactive")


# --- stage 2's staleness bound: TTL + SWR, never more ----------------------


def test_swr_serves_within_window_and_never_past_it():
    cache = ResponseCache(ttl_s=0.15, max_mb=1.0, neg_ttl_s=0.0, swr_s=0.3)
    cache.put("k", b"body", "application/json", "m", "h")
    # Fresh: ordinary hit, not stale.
    assert cache.lookup_swr("k", stale_ok=False) == (
        200, b"body", "application/json", False,
    )
    time.sleep(0.2)  # past TTL, inside the SWR window
    # Without stale_ok (stage < 2) an in-window entry answers None but is
    # NOT evicted -- a brownout arriving later can still use it.
    assert cache.lookup_swr("k", stale_ok=False) is None
    got = cache.lookup_swr("k", stale_ok=True)
    assert got == (200, b"body", "application/json", True)
    assert cache.stale_hits == 1
    time.sleep(0.35)  # past TTL + SWR: gone even for a desperate caller
    assert cache.lookup_swr("k", stale_ok=True) is None
    assert cache.stale_hits == 1


def test_negative_entries_never_get_swr():
    cache = ResponseCache(ttl_s=60.0, max_mb=1.0, neg_ttl_s=0.1, swr_s=30.0)
    cache.put("bad", b"nope", "application/json", "m", "h", status=404)
    assert cache.lookup_swr("bad", stale_ok=True)[0] == 404
    time.sleep(0.15)  # neg TTL expired: a replayed 404 is pure harm
    assert cache.lookup_swr("bad", stale_ok=True) is None


# --- end-to-end over a real gateway + model-server -------------------------


def _two_tier_stack(tmp_path, **gw_kw):
    from functools import partial
    from http.server import HTTPServer, SimpleHTTPRequestHandler

    from PIL import Image

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
    from kubernetes_deep_learning_tpu.serving.gateway import Gateway
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    spec = register_spec(ModelSpec(
        name="brownout-e2e", family="xception",
        input_shape=(32, 32, 3), labels=("a", "b", "c"),
    ))
    root = tmp_path / "models"
    art.save_artifact(
        art.version_dir(str(root), spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        str(root), port=0, buckets=(1, 2), max_delay_ms=1.0, host="127.0.0.1",
        engine_factory=lambda a, **kw: StubEngine(a, **kw),
    )
    server.warmup()
    server.start()
    rng = np.random.default_rng(0)
    Image.fromarray(
        rng.integers(0, 256, size=(48, 48, 3), dtype=np.uint8)
    ).save(tmp_path / "img.png")
    httpd = HTTPServer(
        ("127.0.0.1", 0),
        partial(SimpleHTTPRequestHandler, directory=str(tmp_path)),
    )
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name,
        port=0, host="127.0.0.1", **gw_kw,
    )
    gw.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}/img.png"
    return server, httpd, gw, url


def test_brownout_sheds_classes_and_serves_stale_end_to_end(tmp_path):
    import requests

    from kubernetes_deep_learning_tpu.serving import protocol

    # brownout=False keeps the gateway's own evaluate() daemon off: the
    # test drives the replacement controller's ladder by hand, one stage
    # at a time, so each stage's observable behavior can be pinned.
    server, httpd, gw, url = _two_tier_stack(
        tmp_path, cache=True, cache_ttl_s=0.3, cache_swr_s=30.0,
        brownout=False,
    )
    ctl, slo, clock = _controller(burn=100.0, dwell_s=0.0)
    gw.brownout = ctl
    base = f"http://127.0.0.1:{gw.port}"

    def predict(priority=None):
        headers = {}
        if priority is not None:
            headers[protocol.PRIORITY_HEADER] = priority
        return requests.post(
            f"{base}/predict", json={"url": url}, headers=headers, timeout=30
        )

    try:
        # Healthy (stage 0): the first request fills the cache.
        r = predict()
        assert r.status_code == 200, r.text
        assert r.headers.get(protocol.CACHE_STATUS_HEADER) == "miss"

        ctl.evaluate(), ctl.evaluate()  # -> stage 2
        assert ctl.stage == 2
        time.sleep(0.4)  # TTL-expire the entry; SWR keeps it resident
        r = predict()
        assert r.status_code == 200
        assert r.headers.get(protocol.CACHE_STATUS_HEADER) == "stale"

        ctl.evaluate()  # -> stage 3: best-effort shed, batch still served
        assert ctl.stage == 3
        r = predict(priority="best-effort")
        assert r.status_code == 429
        assert r.json()["shed_reason"] == "brownout"
        assert "Retry-After" in r.headers
        assert predict(priority="batch").status_code == 200

        ctl.evaluate()  # -> stage 4: batch shed too; interactive never
        assert ctl.stage == 4
        r = predict(priority="batch")
        assert r.status_code == 429 and r.json()["shed_reason"] == "brownout"
        assert predict(priority="interactive").status_code == 200

        # The operator surface agrees with what the wire just showed.
        dbg = requests.get(f"{base}/debug/brownout", timeout=5).json()
        assert dbg["stage"] == 4
        assert dbg["actions"] == [
            "hedging disabled", "stale cache serves",
            "shed best-effort", "shed batch",
        ]
        assert dbg["classes"]["best-effort"]["shed"] >= 1
        assert dbg["classes"]["batch"]["shed"] >= 1
        assert dbg["classes"]["interactive"]["shed"] == 0
        metrics = requests.get(f"{base}/metrics", timeout=5).text
        assert (
            'kdlt_admission_class_shed_total{class="best-effort",'
            'tier="gateway"}' in metrics
            or 'class="best-effort"' in metrics
        )
        assert 'shed_reason="brownout"' in metrics
    finally:
        gw.shutdown()
        server.shutdown()
        httpd.shutdown()


def test_budget_isolates_tenant_from_noisy_neighbor(tmp_path, monkeypatch):
    """Per-model budgets at the model tier: tenant A floods all slots and
    queues deep over-share; tenant B's single request must still be
    granted ahead of A's over-share waiters (work-conserving borrowing,
    borrowed capacity handed back first)."""
    import requests

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
    from kubernetes_deep_learning_tpu.serving import protocol
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    monkeypatch.setenv("KDLT_ADMISSION_MAX_CONCURRENCY", "2")
    monkeypatch.setenv("KDLT_ADMISSION_INITIAL_CONCURRENCY", "2")
    monkeypatch.setenv("KDLT_ADMIT_BUDGETS", "nb-a=1,nb-b=1")
    root = tmp_path / "models"
    specs = {}
    for name in ("nb-a", "nb-b"):
        spec = register_spec(ModelSpec(
            name=name, family="xception",
            input_shape=(32, 32, 3), labels=("a", "b", "c"),
        ))
        art.save_artifact(
            art.version_dir(str(root), name, 1), spec, {"params": {}}, None, {}
        )
        specs[name] = spec
    server = ModelServer(
        str(root), port=0, buckets=(1,), max_delay_ms=1.0, host="127.0.0.1",
        engine_factory=lambda a, **kw: StubEngine(
            a, device_ms_per_batch=200.0, **kw
        ),
    )
    server.warmup()
    server.start()
    try:
        limiter = server.admission.limiter
        assert limiter is not None
        assert limiter.budgets == {"nb-a": 1.0, "nb-b": 1.0}

        done: dict = {}

        def hit(tag, model):
            img = np.zeros((1, 32, 32, 3), np.uint8)
            r = requests.post(
                f"http://127.0.0.1:{server.port}/v1/models/{model}:predict",
                data=protocol.encode_predict_request(img),
                headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
                timeout=30,
            )
            done[tag] = (r.status_code, time.monotonic())

        # Tenant A floods: 6 requests against 2 slots, 200 ms serial
        # each -- both slots taken (one borrowed from B) and the queue
        # holds A waiters deep over A's 1-slot share.
        flood = [
            threading.Thread(target=hit, args=(f"a{i}", "nb-a"))
            for i in range(6)
        ]
        for t in flood:
            t.start()
        for _ in range(200):
            if server.admission.inflight >= 2:
                break
            time.sleep(0.01)
        assert server.admission.inflight >= 2
        time.sleep(0.05)  # let the remaining A requests enqueue behind
        # Mid-flood the debug surface shows A's budget: one active model
        # owns the whole limit until B shows up.
        assert limiter.shares().get("nb-a") == limiter.limit
        tb = threading.Thread(target=hit, args=("b", "nb-b"))
        tb.start()
        for t in [*flood, tb]:
            t.join(timeout=30)

        assert done["b"][0] == 200, done
        # B arrived LAST yet finished before A's flood drained: the next
        # free slot went to the under-share owner, not A's earlier
        # waiters.  Without budgets FIFO order would finish B last.
        a_finishes = [done[f"a{i}"][1] for i in range(6)
                      if done[f"a{i}"][0] == 200]
        assert a_finishes, done
        assert done["b"][1] < max(a_finishes), done
    finally:
        server.shutdown()
