"""bench.py's fault-isolation contract (VERDICT r3 #1a), via the real CLI.

A faulting batch point must be retried, recorded in the JSON's ``faults``
list, and must NOT abort the sweep or crash the parent -- one fault
nullified the whole official record in rounds 1-3.  The forced fault here
is an unknown model name: the child dies before any device use (get_spec
raises first), so the test never dials the single-client TPU tunnel.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")


def test_faulted_points_are_recorded_not_fatal():
    proc = subprocess.run(
        [
            sys.executable, _BENCH,
            "--batches", "2,4",
            "--model", "no-such-model",
            "--point-timeout", "120",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        timeout=300,
    )
    # Every point faulted -> rc=1, but the parent still emits its one JSON
    # line with the full fault record (nothing hidden, nothing crashed).
    assert proc.returncode == 1
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["value"] == 0.0 and out["vs_baseline"] == 0.0
    assert "EVERY batch point faulted" in out["metric"]
    # Both points, both attempts each: the sweep continued past the first
    # fault and each fault carries the child's stderr tail.
    attempts = [(f["batch"], f["attempt"]) for f in out["faults"]]
    assert attempts == [(2, 1), (2, 2), (4, 1), (4, 2)]
    assert all("no-such-model" in f["fault"] for f in out["faults"])
