"""bench.py's official-record survivability contract, via the real CLI.

Two failure modes have nullified the driver-captured record in past
rounds, and each has a contract tested here:

* r1-r3: a TPU worker fault in the single shared process killed the whole
  sweep -> per-point subprocess isolation (a faulting batch point must be
  retried, recorded in ``faults``, and must NOT abort the sweep);
* r4 (rc=124): the DRIVER's wall-clock budget killed the sweep before the
  end-of-run JSON printed -> the current-best headline is re-emitted after
  every completed point, an overall --budget-s trims the tail, and SIGTERM
  triggers a final emission -- so the last stdout line parses no matter
  when the run is cut down.

Device-free forcing functions: an unknown model name makes a child die
before any device use (get_spec raises first), and KDLT_BENCH_FAKE_CHILD=1
makes children emit synthetic rows without importing jax -- either way the
tests never dial the single-client TPU tunnel.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")


def _fake_env(sleep_s: float = 0.0) -> dict:
    env = dict(os.environ)
    env["KDLT_BENCH_FAKE_CHILD"] = "1"
    env["KDLT_BENCH_FAKE_CHILD_SLEEP_S"] = str(sleep_s)
    return env


def _parse_lines(stdout: bytes) -> list[dict]:
    lines = [ln for ln in stdout.decode().strip().splitlines() if ln.strip()]
    return [json.loads(ln) for ln in lines]


def test_every_point_emits_a_parsable_headline():
    proc = subprocess.run(
        [sys.executable, _BENCH, "--batches", "4,8,16", "--budget-s", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_fake_env(), timeout=120,
    )
    assert proc.returncode == 0
    outs = _parse_lines(proc.stdout)
    # One emission per completed point plus the final record; EVERY line is
    # a complete same-schema headline, so a cut at any moment still parses.
    assert len(outs) == 4
    for out in outs:
        assert out["unit"] == "images/sec/chip"
        assert out["value"] > 0
        assert "sweep" in out and "metric" in out
    assert [len(o["sweep"]) for o in outs] == [1, 2, 3, 3]
    # Final record equals the last incremental one (later overwrites earlier)
    # except for the progress note dropping once the sweep is complete.
    assert outs[-1]["value"] == outs[-2]["value"]


def test_budget_trims_remaining_points_and_records_them():
    proc = subprocess.run(
        [sys.executable, _BENCH, "--batches", "4,8,16,32", "--budget-s", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_fake_env(sleep_s=0.5), timeout=120,
    )
    outs = _parse_lines(proc.stdout)
    final = outs[-1]
    # The per-point estimate is floored at 60s, so a 3s budget admits only
    # the first point; the rest must be recorded as dropped, not vanish.
    assert final["dropped_points"] == [8, 16, 32]
    assert len(final["sweep"]) == 1
    assert "partial sweep 1/4" in final["metric"]
    assert proc.returncode == 0  # the surviving point is in-bound


def test_sigterm_mid_sweep_still_parses():
    # 5 points x 2s each; SIGTERM lands mid-point-2.  The driver's timeout
    # does exactly this (rc=124 killed round 4's record); the contract is
    # that the last stdout line is still a parsable headline carrying every
    # completed point.
    proc = subprocess.Popen(
        [sys.executable, _BENCH, "--batches", "4,8,16,32,64", "--budget-s", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=_fake_env(sleep_s=2.0),
    )
    # Wait for the first incremental emission so at least one point exists.
    first = proc.stdout.readline()
    assert json.loads(first)["value"] > 0
    proc.send_signal(signal.SIGTERM)
    out_b, _ = proc.communicate(timeout=60)
    outs = _parse_lines(first + out_b)
    final = outs[-1]
    assert final["terminated"] is True
    assert len(final["sweep"]) >= 1
    assert final["value"] > 0
    assert "terminated by signal" in final["metric"]


def test_faulted_points_are_recorded_not_fatal():
    proc = subprocess.run(
        [
            sys.executable, _BENCH,
            "--batches", "2,4",
            "--model", "no-such-model",
            "--point-timeout", "120",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        timeout=300,
    )
    # Every point faulted -> rc=1, but the parent still emits its one JSON
    # line with the full fault record (nothing hidden, nothing crashed).
    assert proc.returncode == 1
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["value"] == 0.0 and out["vs_baseline"] == 0.0
    assert "EVERY batch point faulted" in out["metric"]
    # Both points, both attempts each: the sweep continued past the first
    # fault and each fault carries the child's stderr tail.
    attempts = [(f["batch"], f["attempt"]) for f in out["faults"]]
    assert attempts == [(2, 1), (2, 2), (4, 1), (4, 2)]
    assert all("no-such-model" in f["fault"] for f in out["faults"])
