"""Host-path components: StubEngine, engine_factory injection, gateway
upstream micro-batching.

These are the moving parts of bench.py --host-saturation (the proof that the
HTTP + protocol + batcher path can carry the BASELINE target without the
device, VERDICT r1 weak-3) -- so their correctness is tested in isolation:
checksum logits must be per-image (misrouted batcher responses fail loudly),
and the micro-batcher must coalesce without crossing responses.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.runtime.stub import StubEngine, stub_logits
from kubernetes_deep_learning_tpu.serving.microbatch import UpstreamMicroBatcher


@pytest.fixture(scope="module")
def stub_spec():
    return register_spec(
        ModelSpec(
            name="hostpath-stub",
            family="xception",  # family is never instantiated by StubEngine
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )


@pytest.fixture(scope="module")
def stub_server(stub_spec, tmp_path_factory):
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    root = tmp_path_factory.mktemp("stub-models")
    art.save_artifact(
        art.version_dir(str(root), stub_spec.name, 1), stub_spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        str(root), port=0, buckets=(1, 2, 4, 8), max_delay_ms=1.0,
        host="127.0.0.1", engine_factory=StubEngine,
    )
    server.warmup()
    server.start()
    yield stub_spec, server
    server.shutdown()


def test_stub_logits_distinguish_images(stub_spec):
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, size=(4, *stub_spec.input_shape), dtype=np.uint8)
    out = stub_logits(imgs, stub_spec.num_classes)
    assert out.shape == (4, 3)
    # class offsets are exactly [0, 1, 2] on top of the per-image checksum
    np.testing.assert_array_equal(out[:, 1] - out[:, 0], np.ones(4, np.float32))
    assert len({float(v) for v in out[:, 0]}) > 1  # images distinguish


def test_stub_engine_through_batcher_routes_correctly(stub_spec, stub_server):
    """Concurrent single-image predicts through the REAL server + batcher:
    every client must get its own image's checksum back."""
    import requests

    from kubernetes_deep_learning_tpu.serving import protocol

    spec, server = stub_server
    rng = np.random.default_rng(1)
    imgs = [
        rng.integers(0, 256, size=(1, *spec.input_shape), dtype=np.uint8)
        for _ in range(16)
    ]
    url = f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict"
    results: list = [None] * len(imgs)

    def post(i):
        r = requests.Session().post(
            url,
            data=protocol.encode_predict_request(imgs[i]),
            headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
            timeout=30,
        )
        assert r.status_code == 200
        logits, _ = protocol.decode_predict_response(
            r.content, r.headers["Content-Type"]
        )
        results[i] = np.asarray(logits)

    threads = [threading.Thread(target=post, args=(i,)) for i in range(len(imgs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, img in enumerate(imgs):
        np.testing.assert_array_equal(
            results[i], stub_logits(img, spec.num_classes)
        )


def test_microbatcher_coalesces_and_routes():
    calls: list[int] = []
    labels = ["a", "b"]
    release = threading.Event()

    def predict_batch(images, request_id):
        release.wait(5)  # hold the first flush so followers queue up
        calls.append(images.shape[0])
        return [img.sum() * np.ones(2) for img in images], labels

    mb = UpstreamMicroBatcher(predict_batch, max_batch=8, max_delay_ms=5.0)
    imgs = [np.full((2, 2, 3), i, np.uint8) for i in range(12)]
    results: list = [None] * len(imgs)

    def submit(i):
        results[i] = mb.predict(imgs[i])

    threads = [threading.Thread(target=submit, args=(i,)) for i in range(len(imgs))]
    for t in threads:
        t.start()
    import time

    time.sleep(0.2)  # let every request enqueue behind the held flush
    release.set()
    for t in threads:
        t.join()
    mb.close()

    for i, img in enumerate(imgs):
        row, got_labels = results[i]
        assert got_labels == labels
        np.testing.assert_array_equal(row, img.sum() * np.ones(2))
    assert sum(calls) == len(imgs)
    assert max(calls) > 1  # coalescing actually happened


def test_microbatcher_overlaps_flushes_up_to_pipeline_depth():
    """Pipelined flushes: with depth 2 the dispatcher must START upstream
    flush N+1 while flush N is still in flight (held open here by an
    event), and block at the depth limit -- the gateway-tier mirror of the
    engine's in-flight dispatch pipeline."""
    import time

    started = []
    release = threading.Event()
    labels = ["a", "b"]

    def predict_batch(images, request_id):
        started.append(images.shape[0])
        release.wait(5)  # every flush holds until the test releases
        return [img.sum() * np.ones(2) for img in images], labels

    mb = UpstreamMicroBatcher(
        predict_batch, max_batch=1, max_delay_ms=0.0, pipeline_depth=2
    )
    imgs = [np.full((2, 2, 3), i, np.uint8) for i in range(4)]
    results: list = [None] * len(imgs)
    threads = [
        threading.Thread(target=lambda i=i: results.__setitem__(i, mb.predict(imgs[i])))
        for i in range(len(imgs))
    ]
    for t in threads:
        t.start()
    # Two flushes must be IN FLIGHT concurrently (neither has returned)...
    deadline = time.monotonic() + 5
    while len(started) < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert len(started) == 2
    # ...and the third must be held back by the depth-2 slot limit.
    time.sleep(0.1)
    assert len(started) == 2
    release.set()
    for t in threads:
        t.join()
    mb.close()
    for i, img in enumerate(imgs):
        row, _ = results[i]
        np.testing.assert_array_equal(row, img.sum() * np.ones(2))


def test_microbatcher_propagates_upstream_failure():
    def predict_batch(images, request_id):
        raise RuntimeError("upstream down")

    mb = UpstreamMicroBatcher(predict_batch, max_batch=4, max_delay_ms=1.0)
    with pytest.raises(RuntimeError, match="upstream down"):
        mb.predict(np.zeros((2, 2, 3), np.uint8))
    mb.close()
    with pytest.raises(RuntimeError, match="closed"):
        mb.predict(np.zeros((2, 2, 3), np.uint8))


def test_gateway_upstream_batching_e2e(stub_server, monkeypatch):
    """Gateway with upstream_batch: concurrent /predict single-image requests
    coalesce into fat upstream calls and every client gets its own scores."""
    import requests

    from kubernetes_deep_learning_tpu.serving.gateway import Gateway

    spec, server = stub_server
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}",
        model=spec.name,
        port=0,
        host="127.0.0.1",
        upstream_batch=8,
        upstream_delay_ms=5.0,
    )
    rng = np.random.default_rng(2)
    imgs = {
        f"http://img.test/{i}.png": rng.integers(
            0, 256, size=spec.input_shape, dtype=np.uint8
        )
        for i in range(10)
    }
    monkeypatch.setattr(gw, "_fetch_one", lambda url: imgs[url])
    gw.start()
    try:
        results: dict = {}
        lock = threading.Lock()

        def post(url):
            r = requests.post(
                f"http://127.0.0.1:{gw.port}/predict", json={"url": url}, timeout=30
            )
            assert r.status_code == 200, r.text
            with lock:
                results[url] = r.json()

        threads = [threading.Thread(target=post, args=(u,)) for u in imgs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for url, img in imgs.items():
            want = stub_logits(img[None], spec.num_classes)[0]
            got = np.array([results[url][l] for l in spec.labels])
            np.testing.assert_allclose(got, want, rtol=1e-6)
    finally:
        gw.shutdown()
