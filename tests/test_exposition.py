"""Prometheus text-exposition validity for both tiers' /metrics output.

A minimal strict parser of the exposition format, covering the failure
modes a lenient substring test never catches: duplicate HELP/TYPE blocks
for labeled series sharing a name (the bug Registry.render used to have),
un-escaped label values, ungrouped samples, and non-monotonic histogram
buckets.  Runs against the FULL /metrics page of a live gateway and model
server, so every helper in utils/metrics.py is exercised as rendered.
"""

from __future__ import annotations

import re
import tempfile
import threading

import numpy as np
import pytest
import requests

from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_NAME}) (.*)$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (counter|gauge|histogram|summary|untyped)$")
# One sample line: name{labels} value.  Label values must be properly
# escaped strings; an unescaped '"' or newline breaks this regex and the
# parser fails the page.
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')
_SAMPLE_RE = re.compile(rf"^({_NAME})(\{{.*\}})? ([^ ]+)$")
# OpenMetrics exemplar annotation (KDLT_METRICS_EXEMPLARS=1):
#   name_bucket{le="x"} 12 # {trace_id="abc"} 0.034 1622.5
_EXEMPLAR_RE = re.compile(r"^\{(.*)\} ([^ ]+)( [^ ]+)?$")


class ExpositionError(AssertionError):
    pass


def parse_exposition(text: str) -> dict:
    """Strictly parse a text exposition; returns {base_name: {"type": ...,
    "samples": [(full_name, labels_dict, value)]}}.  Raises
    ExpositionError on any structural violation."""
    families: dict[str, dict] = {}
    current: str | None = None
    seen_done: set[str] = set()  # families whose block has ended

    def base_name(sample_name: str) -> str:
        for fam, info in families.items():
            if info["type"] == "histogram" and sample_name in (
                f"{fam}_bucket", f"{fam}_sum", f"{fam}_count"
            ):
                return fam
        return sample_name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line) or _TYPE_RE.match(line)
            if m is None:
                raise ExpositionError(f"line {lineno}: bad comment {line!r}")
            name = m.group(1)
            key = "help" if line.startswith("# HELP") else "type"
            if name in seen_done:
                raise ExpositionError(
                    f"line {lineno}: metadata for {name!r} after its block "
                    f"ended (duplicate/ungrouped {key.upper()})"
                )
            fam = families.setdefault(name, {"type": None, "help": None, "samples": []})
            if fam[key] is not None:
                raise ExpositionError(
                    f"line {lineno}: duplicate # {key.upper()} for {name!r}"
                )
            fam[key] = m.group(2)
            if current is not None and current != name:
                seen_done.add(current)
            current = name
            continue
        # Split off an OpenMetrics exemplar annotation before the classic
        # sample grammar applies (the annotation is only legal on histogram
        # _bucket samples -- enforced below).
        exemplar = None
        sample_part = line
        if " # " in line:
            sample_part, _, ex_raw = line.partition(" # ")
            em = _EXEMPLAR_RE.match(ex_raw)
            if em is None:
                raise ExpositionError(
                    f"line {lineno}: malformed exemplar {ex_raw!r}"
                )
            ex_labels_raw, ex_value_raw, _ex_ts = em.groups()
            matched = _LABEL_RE.findall(ex_labels_raw)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in matched)
            if rebuilt != ex_labels_raw:
                raise ExpositionError(
                    f"line {lineno}: malformed exemplar labels "
                    f"{ex_labels_raw!r}"
                )
            try:
                exemplar = (dict(matched), float(ex_value_raw))
            except ValueError as e:
                raise ExpositionError(
                    f"line {lineno}: bad exemplar value {ex_value_raw!r}"
                ) from e
        m = _SAMPLE_RE.match(sample_part)
        if m is None:
            raise ExpositionError(f"line {lineno}: unparsable sample {line!r}")
        sample_name, labels_raw, value_raw = m.groups()
        fam_name = base_name(sample_name)
        if fam_name not in families:
            raise ExpositionError(
                f"line {lineno}: sample {sample_name!r} before its TYPE"
            )
        if fam_name in seen_done:
            raise ExpositionError(
                f"line {lineno}: sample of {fam_name!r} outside its block "
                "(all series of one name must be grouped)"
            )
        if current != fam_name:
            if current is not None:
                seen_done.add(current)
            current = fam_name
        labels: dict[str, str] = {}
        if labels_raw:
            inner = labels_raw[1:-1]
            matched = _LABEL_RE.findall(inner)
            # Reconstruct to verify every byte of the label section parsed.
            rebuilt = ",".join(f'{k}="{v}"' for k, v in matched)
            if rebuilt != inner:
                raise ExpositionError(
                    f"line {lineno}: malformed/unescaped labels {labels_raw!r}"
                )
            labels = dict(matched)
        try:
            value = float(value_raw)
        except ValueError as e:
            raise ExpositionError(f"line {lineno}: bad value {value_raw!r}") from e
        families[fam_name]["samples"].append((sample_name, labels, value))
        if exemplar is not None:
            if (
                families[fam_name]["type"] != "histogram"
                or not sample_name.endswith("_bucket")
            ):
                raise ExpositionError(
                    f"line {lineno}: exemplar on non-histogram-bucket sample "
                    f"{sample_name!r}"
                )
            families[fam_name].setdefault("exemplars", []).append(
                (sample_name, labels, exemplar[0], exemplar[1])
            )

    for name, fam in families.items():
        if fam["type"] is None:
            raise ExpositionError(f"{name!r} has samples but no TYPE")
        if fam["type"] == "histogram":
            _check_histogram(name, fam["samples"])
    return families


def _series_key(labels: dict) -> tuple:
    return tuple(sorted((k, v) for k, v in labels.items() if k != "le"))


def _check_histogram(name: str, samples: list) -> None:
    by_series: dict[tuple, dict] = {}
    for sample_name, labels, value in samples:
        entry = by_series.setdefault(
            _series_key(labels), {"buckets": [], "sum": None, "count": None}
        )
        if sample_name == f"{name}_bucket":
            le = labels.get("le")
            if le is None:
                raise ExpositionError(f"{name}: bucket without le label")
            entry["buckets"].append((float("inf") if le == "+Inf" else float(le), value))
        elif sample_name == f"{name}_sum":
            entry["sum"] = value
        elif sample_name == f"{name}_count":
            entry["count"] = value
    for key, entry in by_series.items():
        buckets = entry["buckets"]
        if not buckets:
            raise ExpositionError(f"{name}{dict(key)}: histogram without buckets")
        les = [le for le, _ in buckets]
        if les != sorted(les):
            raise ExpositionError(f"{name}{dict(key)}: le values not ascending")
        if les[-1] != float("inf"):
            raise ExpositionError(f"{name}{dict(key)}: missing +Inf bucket")
        counts = [c for _, c in buckets]
        if any(b < a for a, b in zip(counts, counts[1:])):
            raise ExpositionError(
                f"{name}{dict(key)}: non-monotonic cumulative bucket counts"
            )
        if entry["count"] is None or entry["sum"] is None:
            raise ExpositionError(f"{name}{dict(key)}: missing _sum/_count")
        if entry["count"] != counts[-1]:
            raise ExpositionError(
                f"{name}{dict(key)}: _count {entry['count']} != +Inf bucket "
                f"{counts[-1]}"
            )


# --- parser self-tests (it must actually catch the failure modes) ----------


def test_parser_rejects_duplicate_help_type():
    bad = (
        "# HELP m a\n# TYPE m counter\nm 1\n"
        "# HELP m a\n# TYPE m counter\nm{x=\"y\"} 2\n"
    )
    with pytest.raises(ExpositionError, match="after its block|duplicate"):
        parse_exposition(bad)


def test_parser_rejects_ungrouped_samples():
    bad = (
        "# HELP a h\n# TYPE a counter\na 1\n"
        "# HELP b h\n# TYPE b counter\nb 1\na 2\n"
    )
    with pytest.raises(ExpositionError, match="grouped"):
        parse_exposition(bad)


def test_parser_rejects_unescaped_label_quote():
    bad = '# HELP m h\n# TYPE m counter\nm{x="a"b"} 1\n'
    with pytest.raises(ExpositionError, match="label"):
        parse_exposition(bad)


def test_parser_rejects_non_monotonic_histogram():
    bad = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\nh_bucket{le="+Inf"} 5\n'
        "h_sum 1\nh_count 5\n"
    )
    with pytest.raises(ExpositionError, match="monotonic"):
        parse_exposition(bad)


def test_parser_rejects_exemplar_on_counter():
    bad = '# HELP m h\n# TYPE m counter\nm 1 # {trace_id="abc"} 1 1622.5\n'
    with pytest.raises(ExpositionError, match="non-histogram"):
        parse_exposition(bad)


# --- exemplars: annotated round-trip on, byte-identical legacy off ----------


def test_exemplar_round_trip_with_flag_on(monkeypatch):
    monkeypatch.setenv(metrics_lib.EXEMPLARS_ENV, "1")
    r = metrics_lib.Registry()
    h = r.histogram("kdlt_test_latency_seconds", "h", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="rid-fast")
    h.observe(0.5, exemplar="rid-slow")
    h.observe(0.07)  # later un-exemplared observation keeps the exemplar
    text = r.render()
    fams = parse_exposition(text)  # strict parse survives the annotation
    exemplars = {
        labels["le"]: (ex_labels["trace_id"], value)
        for _name, labels, ex_labels, value
        in fams["kdlt_test_latency_seconds"]["exemplars"]
    }
    # Each exemplar sits on the bucket its observation landed in, carrying
    # the observed value (not the bucket bound).
    assert exemplars["0.1"] == ("rid-fast", 0.05)
    assert exemplars["1.0"] == ("rid-slow", 0.5)


def test_exposition_byte_identical_with_flag_off(monkeypatch):
    monkeypatch.delenv(metrics_lib.EXEMPLARS_ENV, raising=False)

    def build(with_exemplars: bool) -> str:
        r = metrics_lib.Registry()
        r.counter("kdlt_test_total", "c").inc()
        h = r.histogram("kdlt_test_latency_seconds", "h", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar="rid" if with_exemplars else None)
        h.observe(2.0, exemplar="rid2" if with_exemplars else None)
        return r.render()

    # A histogram that RECEIVED exemplars renders byte-identically to one
    # that never did, as long as the env gate is off: legacy scrapers see
    # the exact pre-exemplar exposition.
    assert build(True) == build(False)
    monkeypatch.setenv(metrics_lib.EXEMPLARS_ENV, "1")
    annotated = build(True)
    assert annotated != build(False)
    assert '# {trace_id="rid"}' in annotated


# --- the fix itself: grouped HELP/TYPE for same-name labeled series --------


def test_registry_groups_labeled_series_under_one_block():
    r = metrics_lib.Registry()
    for reason in ("alpha", "beta", "gamma"):
        r.with_labels(shed_reason=reason).counter(
            "kdlt_test_shed_total", "sheds by reason"
        ).inc()
    text = r.render()
    assert text.count("# HELP kdlt_test_shed_total") == 1
    assert text.count("# TYPE kdlt_test_shed_total") == 1
    fams = parse_exposition(text)
    assert len(fams["kdlt_test_shed_total"]["samples"]) == 3


def test_registry_escapes_label_values_and_help():
    r = metrics_lib.Registry()
    r.with_labels(model='we"ird\nname\\x').counter("kdlt_test_total", "h\nelp")
    fams = parse_exposition(r.render())
    ((_, labels, _),) = fams["kdlt_test_total"]["samples"]
    assert labels["model"] == 'we\\"ird\\nname\\\\x'  # escaped wire form


# --- both live tiers' full /metrics pages ----------------------------------


@pytest.fixture(scope="module")
def metrics_stack():
    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
    from kubernetes_deep_learning_tpu.serving import protocol
    from kubernetes_deep_learning_tpu.serving.gateway import Gateway
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    spec = register_spec(
        ModelSpec(
            name="expo-stub", family="xception",
            input_shape=(16, 16, 3), labels=("a", "b"),
        )
    )
    root = tempfile.mkdtemp(prefix="kdlt-expo-")
    art.save_artifact(
        art.version_dir(root, spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        root, port=0, buckets=(1, 2), host="127.0.0.1", batcher_impl="python",
        engine_factory=lambda a, **kw: StubEngine(a, async_device=True, **kw),
    )
    server.warmup()
    server.start()
    gateway = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name, port=0,
        host="127.0.0.1",
    )
    gateway.start()
    # Traffic so histograms/counters carry real observations (and the
    # dispatcher's pipeline-stage series exist with samples).
    img = np.zeros((1, 16, 16, 3), np.uint8)
    requests.post(
        f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict",
        data=protocol.encode_predict_request(img),
        headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
        timeout=30,
    ).raise_for_status()
    yield server, gateway
    gateway.shutdown()
    server.shutdown()


def test_model_server_metrics_page_is_strictly_valid(metrics_stack):
    server, _ = metrics_stack
    text = requests.get(
        f"http://127.0.0.1:{server.port}/metrics", timeout=5
    ).text
    fams = parse_exposition(text)
    # The admission shed counters are the same-name labeled family that
    # used to render duplicate metadata blocks.
    shed = fams["kdlt_admission_shed_total"]
    assert len(shed["samples"]) >= 5
    assert text.count("# TYPE kdlt_admission_shed_total") == 1
    assert "kdlt_pipeline_readback_seconds" in fams


def test_gateway_metrics_page_is_strictly_valid(metrics_stack):
    _, gateway = metrics_stack
    text = requests.get(
        f"http://127.0.0.1:{gateway.port}/metrics", timeout=5
    ).text
    fams = parse_exposition(text)
    assert "kdlt_gateway_request_seconds" in fams
    assert text.count("# TYPE kdlt_admission_shed_total") == 1
