"""Deploy-config consistency: the checks a docker build would catch.

This environment has no container runtime (ROADMAP "Operations"), so the
images cannot be built here; these tests pin everything statically
verifiable instead: dockerfile COPY sources exist, entrypoints name real
console scripts, the k8s manifests wire the ports and env vars the code
actually listens on, and the two tiers' service DNS names line up --
the class of mistakes the reference's guide debugs by kubectl-eye
(reference guide.md:461-581).
"""

from __future__ import annotations

import os
import re

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEPLOY = os.path.join(REPO, "deploy")


def _read(path):
    with open(path) as f:
        return f.read()


def _yaml_docs(path):
    return [d for d in yaml.safe_load_all(_read(path)) if d]


def test_dockerfile_copy_sources_exist():
    for name in ("gateway.dockerfile", "model-server.dockerfile"):
        text = _read(os.path.join(DEPLOY, name))
        for m in re.finditer(r"^\s*COPY\s+(?:--[\w=]+\s+)*(\S+)\s+\S+", text, re.M):
            src = m.group(1)
            if src == "models":
                # Build-time artifact: kdlt-export produces it right before
                # docker build, the same way the reference bakes its
                # SavedModel (reference tf-serving.dockerfile:5).
                continue
            assert os.path.exists(os.path.join(REPO, src)), (
                f"{name}: COPY source {src!r} does not exist in the build context"
            )


def test_dockerfile_entrypoints_are_real_console_scripts():
    # tomllib is stdlib only on 3.11+; requires-python allows 3.10.
    tomllib = pytest.importorskip("tomllib")

    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        scripts = set(tomllib.load(f)["project"]["scripts"])
    for name in ("gateway.dockerfile", "model-server.dockerfile"):
        text = _read(os.path.join(DEPLOY, name))
        used = set(re.findall(r"kdlt-[\w-]+", text))
        missing = {u for u in used if u not in scripts and not u.startswith("kdlt-models")}
        assert not missing, f"{name} invokes unknown scripts {missing}"


def test_k8s_ports_and_env_wiring():
    from kubernetes_deep_learning_tpu.serving.gateway import (
        DEFAULT_PORT as GATEWAY_PORT,
        SERVING_HOST_ENV,
    )
    from kubernetes_deep_learning_tpu.serving.model_server import (
        DEFAULT_PORT as MODEL_PORT,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    (model_svc,) = _yaml_docs(os.path.join(k8s, "model-server-service.yaml"))
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    (gw_svc,) = _yaml_docs(os.path.join(k8s, "gateway-service.yaml"))

    model_container = model_dep["spec"]["template"]["spec"]["containers"][0]
    assert any(
        p["containerPort"] == MODEL_PORT for p in model_container["ports"]
    ), "model-server container must expose its default port"
    assert model_svc["spec"]["ports"][0]["port"] == MODEL_PORT

    gw_container = gw_dep["spec"]["template"]["spec"]["containers"][0]
    assert any(p["containerPort"] == GATEWAY_PORT for p in gw_container["ports"])
    env = {e["name"]: e.get("value", "") for e in gw_container.get("env", [])}
    assert SERVING_HOST_ENV in env, (
        f"gateway Deployment must set {SERVING_HOST_ENV} (the reference's "
        "TF_SERVING_HOST convention)"
    )
    # The discovery value must point at the model Service's DNS name + port.
    svc_name = model_svc["metadata"]["name"]
    assert env[SERVING_HOST_ENV].startswith(svc_name), env[SERVING_HOST_ENV]
    assert env[SERVING_HOST_ENV].endswith(str(MODEL_PORT))
    # LoadBalancer ingress fronts the gateway (reference serving-gateway-service.yaml:8-11)
    assert gw_svc["spec"]["type"] == "LoadBalancer"
    assert gw_svc["spec"]["ports"][0]["targetPort"] == GATEWAY_PORT


def test_k8s_model_server_compile_cache_volume():
    """The persistent-compile-cache wiring must be complete end to end:
    env var -> mount -> volume (utils/compilecache.py; a restarted
    container re-reads compiled bucket programs instead of re-paying ~10
    min of warmup)."""
    from kubernetes_deep_learning_tpu.utils.compilecache import ENV_VAR

    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    pod = model_dep["spec"]["template"]["spec"]
    container = pod["containers"][0]
    env = {e["name"]: e.get("value", "") for e in container.get("env", [])}
    assert ENV_VAR in env, "model server must point the XLA cache at a volume"
    cache_path = env[ENV_VAR]
    mounts = {m["name"]: m["mountPath"] for m in container.get("volumeMounts", [])}
    assert cache_path in mounts.values(), (
        f"{ENV_VAR}={cache_path} must be a mounted volume, not container-"
        "ephemeral filesystem (the whole point is surviving restarts)"
    )
    mount_name = next(n for n, p in mounts.items() if p == cache_path)
    assert any(v["name"] == mount_name for v in pod.get("volumes", []))
    # ADVICE r4: steady-state readiness must evict an unhealthy pod from
    # the endpoint pool quickly; the warmup budget lives on startupProbe.
    assert container["readinessProbe"]["failureThreshold"] <= 5
    assert container["startupProbe"]["failureThreshold"] >= 60


def test_k8s_and_compose_drain_semantics():
    """The graceful-drain wiring (serving.admission): SIGTERM-driven drain
    needs (a) a preStop sleep so the endpoint controller removes the pod
    from the Service BEFORE admission stops, and (b) a termination grace
    period that covers preStop + the KDLT_DRAIN_TIMEOUT_S default (25 s) --
    otherwise kubelet SIGKILLs mid-drain and in-flight batches die anyway."""
    from kubernetes_deep_learning_tpu.serving.admission.controller import (
        DEFAULT_DRAIN_TIMEOUT_S,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    for fname in ("gateway-deployment.yaml", "model-server-deployment.yaml"):
        (dep,) = _yaml_docs(os.path.join(k8s, fname))
        pod = dep["spec"]["template"]["spec"]
        container = pod["containers"][0]
        grace = pod.get("terminationGracePeriodSeconds", 30)
        pre_stop = container.get("lifecycle", {}).get("preStop")
        assert pre_stop is not None, f"{fname}: no preStop hook"
        sleep_s = float(pre_stop["exec"]["command"][-1])
        assert grace >= sleep_s + DEFAULT_DRAIN_TIMEOUT_S, (
            f"{fname}: grace {grace}s cannot cover preStop {sleep_s}s + "
            f"drain {DEFAULT_DRAIN_TIMEOUT_S}s"
        )
        # Drain flips /readyz, so readiness MUST probe /readyz for the
        # endpoint eviction half of the story to exist at all.
        assert container["readinessProbe"]["httpGet"]["path"] == "/readyz", fname

    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    for name, svc in compose["services"].items():
        grace = svc.get("stop_grace_period", "10s")
        assert float(str(grace).rstrip("s")) >= DEFAULT_DRAIN_TIMEOUT_S, (
            f"compose service {name}: stop_grace_period {grace} cannot cover "
            f"the {DEFAULT_DRAIN_TIMEOUT_S}s drain budget"
        )


def test_k8s_model_tier_replicated_for_failover():
    """The serving-path fault-tolerance wiring (serving/upstream.py): the
    model tier runs >= 2 replicas behind a headless Service, the gateway
    discovers them by re-resolving that Service's DNS name live
    (KDLT_POOL_RESOLVE_S dynamic membership -- an HPA scale-up changes the
    upstream pool with NO gateway redeploy), and the hedge/probe knobs
    are set."""
    from kubernetes_deep_learning_tpu.serving.gateway import SERVING_HOST_ENV
    from kubernetes_deep_learning_tpu.serving.model_server import (
        DEFAULT_PORT as MODEL_PORT,
    )
    from kubernetes_deep_learning_tpu.serving.upstream import (
        HEDGE_DELAY_ENV,
        POOL_RESOLVE_ENV,
        PROBE_INTERVAL_ENV,
        parse_hosts,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    (model_svc,) = _yaml_docs(os.path.join(k8s, "model-server-service.yaml"))
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))

    assert model_dep["spec"]["replicas"] >= 2, (
        "failover needs a survivor: the model tier must run >= 2 replicas"
    )
    # Stable per-replica DNS requires a StatefulSet behind a headless Service
    # -- and headless is what makes the Service name resolve to EVERY ready
    # pod address, which is what the gateway's re-resolver diffs.
    assert model_dep["kind"] == "StatefulSet"
    assert model_dep["spec"]["serviceName"] == model_svc["metadata"]["name"]
    assert model_svc["spec"].get("clusterIP") is None or (
        model_svc["spec"]["clusterIP"] == "None"
    )

    gw_container = gw_dep["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e.get("value", "") for e in gw_container.get("env", [])}
    hosts = parse_hosts(env[SERVING_HOST_ENV])
    svc_name = model_svc["metadata"]["name"]
    # Dynamic membership: the gateway names the headless Service itself
    # (one name resolving to the whole fleet), not a static per-pod list
    # that every scale event would have to edit.
    assert len(hosts) == 1, (
        f"{SERVING_HOST_ENV} should name the headless Service once and let "
        f"re-resolution track the fleet, got {hosts}"
    )
    assert hosts[0].startswith(f"{svc_name}."), hosts[0]
    assert hosts[0].endswith(str(MODEL_PORT)), hosts[0]
    assert float(env[POOL_RESOLVE_ENV]) > 0, (
        "dynamic membership wired off: a scale-up would never join the pool"
    )
    assert float(env[HEDGE_DELAY_ENV]) > 0, "hedging must be wired on"
    assert float(env[PROBE_INTERVAL_ENV]) > 0, "active probing must be on"

    # Readiness tuned for failover: with a survivor carrying the tier,
    # eviction latency IS failover latency -- a dead replica must leave the
    # endpoint pool within a few seconds.
    model_container = model_dep["spec"]["template"]["spec"]["containers"][0]
    probe = model_container["readinessProbe"]
    assert probe["periodSeconds"] * probe["failureThreshold"] <= 6, (
        "readiness eviction must complete within a few seconds for failover"
    )


def test_compose_has_second_model_replica_wired_for_failover():
    """docker-compose: two model-server replicas, the gateway's
    KDLT_SERVING_HOST listing both, hedging configured -- the compose-local
    topology bench.py --chaos-ab models."""
    from kubernetes_deep_learning_tpu.serving.gateway import SERVING_HOST_ENV
    from kubernetes_deep_learning_tpu.serving.upstream import (
        HEDGE_DELAY_ENV,
        parse_hosts,
    )

    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    services = compose["services"]
    gw_env = services["gateway"]["environment"]
    hosts = parse_hosts(str(gw_env[SERVING_HOST_ENV]))
    assert len(hosts) >= 2, "gateway must be wired with a replica list"
    model_services = [h.split(":")[0] for h in hosts]
    for name in model_services:
        assert name in services, f"replica list names unknown service {name!r}"
        # Every listed replica is a model-server build with a healthcheck
        # (the gateway's depends_on gates on it).
        assert "model-server" in services[name]["build"]["dockerfile"]
        assert "healthcheck" in services[name]
        assert name in services["gateway"]["depends_on"]
    assert float(gw_env[HEDGE_DELAY_ENV]) > 0


def test_prometheus_scrape_annotations():
    """Observability wiring (ISSUE 4 satellite): both tiers' pod templates
    carry the prometheus.io scrape annotations, pointed at /metrics on the
    port the container actually serves; the compose topology carries the
    equivalent labels so a docker_sd-configured Prometheus discovers the
    local stack the same way."""
    from kubernetes_deep_learning_tpu.serving.gateway import (
        DEFAULT_PORT as GATEWAY_PORT,
    )
    from kubernetes_deep_learning_tpu.serving.model_server import (
        DEFAULT_PORT as MODEL_PORT,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    for fname, port in (
        ("gateway-deployment.yaml", GATEWAY_PORT),
        ("model-server-deployment.yaml", MODEL_PORT),
    ):
        (dep,) = _yaml_docs(os.path.join(k8s, fname))
        tmpl = dep["spec"]["template"]["metadata"]
        ann = tmpl.get("annotations", {})
        assert ann.get("prometheus.io/scrape") == "true", fname
        assert ann.get("prometheus.io/path") == "/metrics", fname
        assert ann.get("prometheus.io/port") == str(port), fname
        # The advertised scrape port must be one the container exposes.
        container = dep["spec"]["template"]["spec"]["containers"][0]
        assert any(
            p["containerPort"] == port for p in container["ports"]
        ), fname

    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    for name, svc in compose["services"].items():
        labels = svc.get("labels", {})
        assert labels.get("prometheus.io/scrape") == "true", (
            f"compose service {name!r} missing scrape labels"
        )
        assert labels.get("prometheus.io/path") == "/metrics", name


def test_deploy_wires_structured_logs_and_profile_dir():
    """The tracing/observability env wiring: JSON request logs on both
    tiers (k8s + compose), and the model tier's KDLT_PROFILE_DIR pointed
    at a mounted volume so /debug/profile captures survive and can be
    copied out."""
    from kubernetes_deep_learning_tpu.serving.model_server import (
        PROFILE_DIR_ENV,
    )
    from kubernetes_deep_learning_tpu.serving.tracing import LOG_FORMAT_ENV

    k8s = os.path.join(DEPLOY, "k8s")
    for fname in ("gateway-deployment.yaml", "model-server-deployment.yaml"):
        (dep,) = _yaml_docs(os.path.join(k8s, fname))
        container = dep["spec"]["template"]["spec"]["containers"][0]
        env = {e["name"]: e.get("value", "") for e in container.get("env", [])}
        assert env.get(LOG_FORMAT_ENV) == "json", fname

    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    container = model_dep["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e.get("value", "") for e in container.get("env", [])}
    profile_dir = env[PROFILE_DIR_ENV]
    mounts = [m["mountPath"] for m in container.get("volumeMounts", [])]
    assert any(profile_dir.startswith(m) for m in mounts), (
        f"{PROFILE_DIR_ENV}={profile_dir} must live under a mounted volume"
    )

    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    for name, svc in compose["services"].items():
        assert str(svc.get("environment", {}).get(LOG_FORMAT_ENV)) == "json", (
            f"compose service {name!r} missing {LOG_FORMAT_ENV}=json"
        )


def test_deploy_wires_crosshost_pipeline_envs():
    """Cross-host dispatch pipelining (ISSUE 5): the model tier carries the
    fleet-wide in-flight budget and follower stall-detection envs in both
    deploy targets, with values the code would actually accept (every
    process of a fleet must agree on the depth, so it must come from the
    manifest, not per-pod defaults)."""
    from kubernetes_deep_learning_tpu.parallel.crosshost import (
        XH_PIPELINE_DEPTH_ENV,
        XH_STALL_FLOOR_S_ENV,
        XH_STALL_MULTIPLE_ENV,
        resolve_xh_pipeline_depth,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    container = model_dep["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e.get("value", "") for e in container.get("env", [])}
    for name in (
        XH_PIPELINE_DEPTH_ENV, XH_STALL_FLOOR_S_ENV, XH_STALL_MULTIPLE_ENV
    ):
        assert name in env, f"model tier must set {name}"
    depth = resolve_xh_pipeline_depth(int(env[XH_PIPELINE_DEPTH_ENV]))
    assert depth == int(env[XH_PIPELINE_DEPTH_ENV]) >= 1
    assert float(env[XH_STALL_FLOOR_S_ENV]) > 0, "stall detection wired off"
    assert float(env[XH_STALL_MULTIPLE_ENV]) >= 1.0

    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    services = compose["services"]
    replicas = [
        name for name, svc in services.items()
        if isinstance(svc.get("build"), dict)
        and "model-server" in svc["build"].get("dockerfile", "")
    ]
    assert len(replicas) >= 2
    depths = set()
    for name in replicas:
        env = services[name].get("environment", {})
        for var in (
            XH_PIPELINE_DEPTH_ENV, XH_STALL_FLOOR_S_ENV, XH_STALL_MULTIPLE_ENV
        ):
            assert var in env, f"compose service {name!r} missing {var}"
        depths.add(str(env[XH_PIPELINE_DEPTH_ENV]))
    # The budget is a fleet-wide protocol parameter: replicas must agree.
    assert len(depths) == 1, f"replicas disagree on the depth: {depths}"


def test_multimodel_scheduler_and_default_model_wiring():
    """Multi-model serving (ISSUE 6): the model tier carries the unified
    scheduler's policy + per-model weight envs in BOTH deploy targets with
    values the code accepts, every model-tier replica agrees (the gateway
    fails over between them -- a replica on a different policy serves a
    different latency profile), the gateway's default-model env matches
    between k8s and compose, and the default model's weight is pinned so a
    second baked-in model cannot silently dilute its share."""
    from kubernetes_deep_learning_tpu.runtime.scheduler import (
        SCHED_POLICY_ENV,
        SCHED_WEIGHTS_ENV,
        resolve_policy,
        resolve_weights,
    )
    from kubernetes_deep_learning_tpu.serving.gateway import MODEL_ENV

    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    container = model_dep["spec"]["template"]["spec"]["containers"][0]
    k8s_env = {e["name"]: e.get("value", "") for e in container.get("env", [])}
    assert SCHED_POLICY_ENV in k8s_env, "model tier must pin the policy"
    assert resolve_policy(k8s_env[SCHED_POLICY_ENV]) == k8s_env[SCHED_POLICY_ENV]
    assert SCHED_WEIGHTS_ENV in k8s_env
    k8s_weights = resolve_weights(k8s_env[SCHED_WEIGHTS_ENV])
    assert k8s_weights, "weights env must parse to at least one entry"

    gw_env = {
        e["name"]: e.get("value", "")
        for e in gw_dep["spec"]["template"]["spec"]["containers"][0]["env"]
    }
    default_model = gw_env[MODEL_ENV]
    assert default_model, "gateway must pin the default model"
    assert default_model in k8s_weights, (
        "the default model's scheduling weight must be pinned explicitly"
    )

    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    services = compose["services"]
    assert str(services["gateway"]["environment"][MODEL_ENV]) == default_model, (
        "k8s and compose must agree on the default model"
    )
    replicas = [
        name for name, svc in services.items()
        if isinstance(svc.get("build"), dict)
        and "model-server" in svc["build"].get("dockerfile", "")
    ]
    assert len(replicas) >= 2
    for name in replicas:
        env = services[name].get("environment", {})
        assert str(env.get(SCHED_POLICY_ENV)) == k8s_env[SCHED_POLICY_ENV], (
            f"compose replica {name!r} disagrees with k8s on the policy"
        )
        assert resolve_weights(str(env.get(SCHED_WEIGHTS_ENV))) == k8s_weights, (
            f"compose replica {name!r} disagrees with k8s on the weights"
        )


def test_gateway_cache_envs_agree_across_k8s_and_compose():
    """The response-cache wiring (ISSUE 8): the gateway carries the
    KDLT_CACHE_* envs in BOTH deploy targets with values the code accepts,
    and the two topologies agree -- a compose stack used to rehearse a
    k8s rollout must exhibit the same caching behavior (hit ratios,
    staleness window, memory budget)."""
    from kubernetes_deep_learning_tpu.serving.cache import (
        CACHE_ENV,
        MAX_MB_ENV,
        TTL_ENV,
        cache_enabled,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    gw_container = gw_dep["spec"]["template"]["spec"]["containers"][0]
    k8s_env = {
        e["name"]: str(e.get("value", "")) for e in gw_container["env"]
    }
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    compose_env = {
        k: str(v)
        for k, v in compose["services"]["gateway"]["environment"].items()
    }
    for var in (CACHE_ENV, TTL_ENV, MAX_MB_ENV):
        assert var in k8s_env, f"k8s gateway must set {var}"
        assert var in compose_env, f"compose gateway must set {var}"
        assert k8s_env[var] == compose_env[var], (
            f"{var} disagrees: k8s={k8s_env[var]!r} "
            f"compose={compose_env[var]!r}"
        )
    # The values must parse as a usable configuration: cache enabled, a
    # positive staleness bound, a positive byte budget.
    os.environ[CACHE_ENV] = k8s_env[CACHE_ENV]
    try:
        assert cache_enabled() is True, "deploys must not ship the kill switch"
    finally:
        del os.environ[CACHE_ENV]
    assert float(k8s_env[TTL_ENV]) > 0, "TTL wired off"
    assert float(k8s_env[MAX_MB_ENV]) > 0, "byte budget wired off"


def test_quant_envs_agree_across_k8s_and_compose():
    """The full-int8 serving wiring (ISSUE 9): the model tier carries
    KDLT_QUANT_TOL + KDLT_QUANT_SCHEME in BOTH deploy targets (and on both
    compose replicas) with values the code accepts, and every copy agrees
    -- a replica with a looser tolerance bound would activate a w8a8
    program its siblings refused, and the gateway fails over between
    them."""
    from kubernetes_deep_learning_tpu.ops.quantize import (
        QUANT_SCHEME_ENV,
        QUANT_TOL_ENV,
        resolve_quant_tol,
        resolve_scheme_override,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    (container,) = model_dep["spec"]["template"]["spec"]["containers"]
    k8s_env = {e["name"]: str(e.get("value", "")) for e in container["env"]}
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    envs = {"k8s/model-server": k8s_env}
    for svc in ("model-server", "model-server-b"):
        envs[f"compose/{svc}"] = {
            k: str(v)
            for k, v in compose["services"][svc]["environment"].items()
        }
    for var in (QUANT_TOL_ENV, QUANT_SCHEME_ENV):
        values = {where: env.get(var) for where, env in envs.items()}
        assert all(v is not None for v in values.values()), (
            f"{var} missing from some model tier: {values}"
        )
        assert len(set(values.values())) == 1, (
            f"{var} disagrees across the model tiers: {values}"
        )
    # The values must parse as a usable configuration through the same
    # resolvers the engine uses.
    tol = float(k8s_env[QUANT_TOL_ENV])
    assert 0.0 < tol < 1.0, "tolerance gate wired to a nonsense bound"
    os.environ[QUANT_TOL_ENV] = k8s_env[QUANT_TOL_ENV]
    os.environ[QUANT_SCHEME_ENV] = k8s_env[QUANT_SCHEME_ENV]
    try:
        assert resolve_quant_tol() == tol
        assert resolve_scheme_override() == "auto", (
            "deploys must not ship the weight-only rollback knob engaged"
        )
    finally:
        del os.environ[QUANT_TOL_ENV]
        del os.environ[QUANT_SCHEME_ENV]


def test_mesh_env_agrees_across_k8s_and_compose():
    """The model-parallel mesh wiring (ISSUE 16): KDLT_MESH_MODEL_PARALLEL
    rides on BOTH deploy targets (and on both compose replicas) with a
    value the resolver accepts, and every copy agrees -- the gateway
    hedges between replicas, and a pair disagreeing on mesh layout would
    serve different latency/memory profiles under the same artifact."""
    from kubernetes_deep_learning_tpu.serving.model_server import (
        MESH_MODEL_PARALLEL_ENV,
        resolve_mesh_model_parallel,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    (container,) = model_dep["spec"]["template"]["spec"]["containers"]
    k8s_env = {e["name"]: str(e.get("value", "")) for e in container["env"]}
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    envs = {"k8s/model-server": k8s_env}
    for svc in ("model-server", "model-server-b"):
        envs[f"compose/{svc}"] = {
            k: str(v)
            for k, v in compose["services"][svc]["environment"].items()
        }
    values = {where: env.get(MESH_MODEL_PARALLEL_ENV) for where, env in envs.items()}
    assert all(v is not None for v in values.values()), (
        f"{MESH_MODEL_PARALLEL_ENV} missing from some model tier: {values}"
    )
    assert len(set(values.values())) == 1, (
        f"{MESH_MODEL_PARALLEL_ENV} disagrees across the model tiers: {values}"
    )
    # The value must parse through the same resolver the server uses, and
    # the CLI flag must still win over it.
    os.environ[MESH_MODEL_PARALLEL_ENV] = k8s_env[MESH_MODEL_PARALLEL_ENV]
    try:
        mp = resolve_mesh_model_parallel()
        assert mp >= 1, "mesh knob wired to a nonsense degree"
        assert resolve_mesh_model_parallel(explicit=4) == 4
    finally:
        del os.environ[MESH_MODEL_PARALLEL_ENV]


def test_isolation_and_brownout_envs_agree_across_k8s_and_compose():
    """The tenant-isolation wiring (ISSUE 12): per-model admission budgets
    on EVERY tier copy (a replica pair disagreeing on partitioning would
    shed different tenants under the same overload), and the brownout
    ladder + SWR window on both gateway deploys, with values the code's
    own resolvers accept."""
    from kubernetes_deep_learning_tpu.serving.admission.brownout import (
        BROWNOUT_ENV,
        BURN_ENTER_ENV,
        BURN_EXIT_ENV,
        brownout_enabled,
    )
    from kubernetes_deep_learning_tpu.serving.admission.limiter import (
        BUDGETS_ENV,
        env_budgets,
    )
    from kubernetes_deep_learning_tpu.serving.cache import SWR_ENV, TTL_ENV

    k8s = os.path.join(DEPLOY, "k8s")
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    (gw_container,) = gw_dep["spec"]["template"]["spec"]["containers"]
    k8s_gw = {e["name"]: str(e.get("value", "")) for e in gw_container["env"]}
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    (model_container,) = model_dep["spec"]["template"]["spec"]["containers"]
    k8s_model = {
        e["name"]: str(e.get("value", "")) for e in model_container["env"]
    }
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))

    def compose_env(svc):
        return {
            k: str(v)
            for k, v in compose["services"][svc]["environment"].items()
        }

    # Budgets: present and agreeing on every copy of every tier.
    budget_copies = {
        "k8s/gateway": k8s_gw.get(BUDGETS_ENV),
        "k8s/model-server": k8s_model.get(BUDGETS_ENV),
        "compose/gateway": compose_env("gateway").get(BUDGETS_ENV),
        "compose/model-server": compose_env("model-server").get(BUDGETS_ENV),
        "compose/model-server-b": compose_env("model-server-b").get(BUDGETS_ENV),
    }
    assert all(v is not None for v in budget_copies.values()), budget_copies
    assert len(set(budget_copies.values())) == 1, budget_copies
    # ... and the shipped value ENABLES partitioning through the code's
    # own resolver (None would be the legacy shared limiter).
    os.environ[BUDGETS_ENV] = k8s_model[BUDGETS_ENV]
    try:
        assert env_budgets() is not None, "deploys ship the legacy limiter"
    finally:
        del os.environ[BUDGETS_ENV]

    # Brownout ladder + SWR: both gateway deploys, agreeing.
    compose_gw = compose_env("gateway")
    for var in (BROWNOUT_ENV, BURN_ENTER_ENV, BURN_EXIT_ENV, SWR_ENV):
        assert var in k8s_gw, f"k8s gateway must set {var}"
        assert var in compose_gw, f"compose gateway must set {var}"
        assert k8s_gw[var] == compose_gw[var], (
            f"{var} disagrees: k8s={k8s_gw[var]!r} compose={compose_gw[var]!r}"
        )
    os.environ[BROWNOUT_ENV] = k8s_gw[BROWNOUT_ENV]
    try:
        assert brownout_enabled() is True, "deploys ship the kill switch"
    finally:
        del os.environ[BROWNOUT_ENV]
    enter = float(k8s_gw[BURN_ENTER_ENV])
    exit_ = float(k8s_gw[BURN_EXIT_ENV])
    assert 0.0 < exit_ < enter, (
        "hysteresis requires exit strictly inside (0, enter)"
    )
    # The SWR window only matters under brownout; it must be positive and
    # it bounds worst-case staleness to TTL + SWR, so keep it sane vs TTL.
    assert float(k8s_gw[SWR_ENV]) > 0, "SWR wired off"
    assert float(k8s_gw[SWR_ENV]) <= 10 * float(k8s_gw[TTL_ENV])


def test_gateway_negative_cache_ttl_wired():
    """Negative caching (ROADMAP cache follow-on #1): both gateway deploys
    carry KDLT_CACHE_NEG_TTL_S, agreeing, positive (the feature is ON in
    production), and within the positive TTL (a negative entry must never
    outlive a positive one)."""
    from kubernetes_deep_learning_tpu.serving.cache import NEG_TTL_ENV, TTL_ENV

    k8s = os.path.join(DEPLOY, "k8s")
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    (container,) = gw_dep["spec"]["template"]["spec"]["containers"]
    k8s_env = {e["name"]: str(e.get("value", "")) for e in container["env"]}
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    compose_env = {
        k: str(v)
        for k, v in compose["services"]["gateway"]["environment"].items()
    }
    assert NEG_TTL_ENV in k8s_env and NEG_TTL_ENV in compose_env
    assert k8s_env[NEG_TTL_ENV] == compose_env[NEG_TTL_ENV]
    neg = float(k8s_env[NEG_TTL_ENV])
    assert 0 < neg <= float(k8s_env[TTL_ENV])


def test_model_server_hpa_scales_on_minted_serving_signals():
    """The model-tier HPA (ROADMAP multi-model gap #4) must scale on metric
    names the serving path actually mints: every metric named in the HPA
    must appear as a literal series name in utils/metrics.py (the single
    minting point check_metrics.py enforces), and the scale target must be
    the StatefulSet the deployment manifest declares."""
    k8s = os.path.join(DEPLOY, "k8s")
    (hpa,) = _yaml_docs(os.path.join(k8s, "model-server-hpa.yaml"))
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))

    ref = hpa["spec"]["scaleTargetRef"]
    assert ref["kind"] == model_dep["kind"]
    assert ref["name"] == model_dep["metadata"]["name"]

    metrics_src = _read(os.path.join(
        REPO, "kubernetes_deep_learning_tpu", "utils", "metrics.py"
    ))
    names = [
        m["pods"]["metric"]["name"]
        for m in hpa["spec"]["metrics"] if m["type"] == "Pods"
    ]
    assert "kdlt_slo_burn_rate" in names, (
        "the HPA must consume the SLO engine's burn-rate signal"
    )
    assert "kdlt_sched_floor_boosts_total" in names, (
        "the HPA must consume the scheduler's starvation-floor signal"
    )
    assert "kdlt_admission_shed_total" in names, (
        "the HPA must consume the admission shed rate (the leading "
        "overload edge -- sheds fire before the burn windows move)"
    )
    assert "kdlt_sched_queue_depth" in names, (
        "the HPA must consume the scheduler queue depth (a standing "
        "queue is the knee before deadline misses)"
    )
    for name in names:
        assert f'"{name}"' in metrics_src, (
            f"HPA scales on {name!r}, which utils/metrics.py does not mint "
            "-- the autoscaler would read a nonexistent series"
        )
    # The burn-rate metric must select a real SLO window label value.
    from kubernetes_deep_learning_tpu.utils import slo as slo_lib

    (burn,) = [
        m["pods"]["metric"] for m in hpa["spec"]["metrics"]
        if m["type"] == "Pods" and m["pods"]["metric"]["name"] == "kdlt_slo_burn_rate"
    ]
    window = burn["selector"]["matchLabels"]["window"]
    assert window in [label for label, _ in slo_lib.WINDOWS]


def test_gateway_hpa_scales_on_minted_shed_signal():
    """The gateway HPA must scale on the admission shed rate -- a signal
    the gateway itself mints -- not CPU (a gateway stalled on slow
    upstreams sheds while its CPU idles); and every metric it names must
    be a literal series name in utils/metrics.py."""
    k8s = os.path.join(DEPLOY, "k8s")
    docs = _yaml_docs(os.path.join(k8s, "gateway-hpa.yaml"))
    (hpa,) = [d for d in docs if d["kind"] == "HorizontalPodAutoscaler"]
    assert hpa["spec"]["scaleTargetRef"]["name"] == "serving-gateway"

    metrics = hpa["spec"]["metrics"]
    assert not any(m["type"] == "Resource" for m in metrics), (
        "CPU-based scaling must be gone: shed rate is the load signal"
    )
    names = [
        m["pods"]["metric"]["name"] for m in metrics if m["type"] == "Pods"
    ]
    assert "kdlt_admission_shed_total" in names
    metrics_src = _read(os.path.join(
        REPO, "kubernetes_deep_learning_tpu", "utils", "metrics.py"
    ))
    for name in names:
        assert f'"{name}"' in metrics_src, (
            f"HPA scales on {name!r}, which utils/metrics.py does not mint"
        )


def test_elastic_fleet_envs_agree_across_k8s_and_compose():
    """Elastic-fleet wiring (ISSUE 11): the gateway's dynamic-membership
    resolve interval and the model tier's AOT-warm boot flag are present
    in BOTH deploy targets with values the code accepts, and the two
    topologies agree -- a compose stack rehearsing a k8s rollout must
    exhibit the same membership churn and warm-boot behavior."""
    from kubernetes_deep_learning_tpu.serving.model_server import AOT_WARM_ENV
    from kubernetes_deep_learning_tpu.serving.upstream import POOL_RESOLVE_ENV

    k8s = os.path.join(DEPLOY, "k8s")
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    services = compose["services"]

    def k8s_env(dep):
        (container,) = dep["spec"]["template"]["spec"]["containers"]
        return {e["name"]: str(e.get("value", "")) for e in container["env"]}

    resolve = {
        "k8s/gateway": k8s_env(gw_dep).get(POOL_RESOLVE_ENV),
        "compose/gateway": str(
            services["gateway"]["environment"].get(POOL_RESOLVE_ENV)
        ),
    }
    assert all(v not in (None, "None") for v in resolve.values()), resolve
    assert len(set(resolve.values())) == 1, (
        f"{POOL_RESOLVE_ENV} disagrees across gateways: {resolve}"
    )
    assert float(next(iter(resolve.values()))) > 0

    warm = {"k8s/model-server": k8s_env(model_dep).get(AOT_WARM_ENV)}
    for svc in ("model-server", "model-server-b"):
        warm[f"compose/{svc}"] = str(
            services[svc]["environment"].get(AOT_WARM_ENV)
        )
    assert all(v not in (None, "None") for v in warm.values()), warm
    assert len(set(warm.values())) == 1, (
        f"{AOT_WARM_ENV} disagrees across the model tiers: {warm}"
    )
    # The value must be one the server's truthy parse accepts.
    assert next(iter(warm.values())).strip().lower() in ("1", "true", "yes")

    # The image-build half of the warm story: the model-server dockerfile
    # bakes the cache with the kdlt-warm console script.
    dockerfile = _read(os.path.join(DEPLOY, "model-server.dockerfile"))
    assert "kdlt-warm" in dockerfile, (
        "model-server.dockerfile must bake the compile cache (kdlt-warm)"
    )


def test_slo_target_agrees_across_every_tier_and_topology():
    """KDLT_SLO_TARGET drives burn rates on BOTH tiers (gateway = client-
    observed, model tier = server-side) and in both topologies; a
    disagreement would make the two views burn at different rates against
    the same traffic, by construction."""
    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    (compose,) = _yaml_docs(os.path.join(DEPLOY, "docker-compose.yaml"))

    def k8s_env(dep, name):
        (container,) = dep["spec"]["template"]["spec"]["containers"]
        return {e["name"]: e.get("value") for e in container["env"]}.get(name)

    targets = {
        "k8s/model-server": k8s_env(model_dep, "KDLT_SLO_TARGET"),
        "k8s/gateway": k8s_env(gw_dep, "KDLT_SLO_TARGET"),
    }
    for svc_name, svc in compose["services"].items():
        targets[f"compose/{svc_name}"] = (
            svc.get("environment", {}).get("KDLT_SLO_TARGET")
        )
    assert all(v is not None for v in targets.values()), targets
    assert len(set(targets.values())) == 1, (
        f"KDLT_SLO_TARGET disagrees across tiers: {targets}"
    )
    # And the value must parse as a usable target.
    from kubernetes_deep_learning_tpu.utils import slo as slo_lib

    value = float(next(iter(targets.values())))
    assert 0.0 < value < 1.0
    assert slo_lib.resolve_target(value) == value


def test_incident_recorder_envs_agree_across_k8s_and_compose():
    """Incident flight-recorder wiring (ISSUE 13): every tier copy in both
    topologies carries the KDLT_INCIDENT_* knobs with values the recorder's
    own parsers accept, the trigger spec / caps agree everywhere (a replica
    pair disagreeing on triggers would capture different incidents for the
    same outage), each tier's bundle dir agrees between compose and k8s,
    and the k8s dirs live on mounted volumes so bundles survive container
    restarts."""
    from kubernetes_deep_learning_tpu.utils.flightrecorder import (
        DIR_ENV,
        MAX_BUNDLES_ENV,
        MAX_MB_ENV,
        TRIGGERS_ENV,
        parse_triggers,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    services = compose["services"]

    def k8s_env(dep):
        (container,) = dep["spec"]["template"]["spec"]["containers"]
        return {e["name"]: str(e.get("value", "")) for e in container["env"]}

    def compose_env(svc):
        return {
            k: str(v) for k, v in services[svc]["environment"].items()
        }

    copies = {
        "k8s/gateway": k8s_env(gw_dep),
        "k8s/model-server": k8s_env(model_dep),
        "compose/gateway": compose_env("gateway"),
        "compose/model-server": compose_env("model-server"),
        "compose/model-server-b": compose_env("model-server-b"),
    }
    # Triggers + caps: present everywhere and identical everywhere.
    for var in (TRIGGERS_ENV, MAX_BUNDLES_ENV, MAX_MB_ENV):
        values = {where: env.get(var) for where, env in copies.items()}
        assert all(v is not None for v in values.values()), (
            f"{var} missing from some tier copy: {values}"
        )
        assert len(set(values.values())) == 1, (
            f"{var} disagrees across tier copies: {values}"
        )
    # The trigger spec must parse through the recorder's own grammar and
    # keep the default rules armed (the deploys must not silently disable
    # a trigger class the runbooks rely on).
    triggers = parse_triggers(copies["k8s/gateway"][TRIGGERS_ENV])
    for name in ("burn-crossing", "brownout", "dispatch-stall",
                 "replica-unhealthy"):
        assert name in triggers, f"deploys dropped the {name} trigger"
    assert int(copies["k8s/gateway"][MAX_BUNDLES_ENV]) > 0
    assert float(copies["k8s/gateway"][MAX_MB_ENV]) > 0

    # Per-tier dir agreement between compose and k8s (the tiers may use
    # different paths -- gateway has no XLA cache volume -- but each
    # tier's compose rehearsal must write where its k8s pod writes).
    for a, b in (("k8s/gateway", "compose/gateway"),
                 ("k8s/model-server", "compose/model-server")):
        assert copies[a].get(DIR_ENV), f"{a} missing {DIR_ENV}"
        assert copies[a][DIR_ENV] == copies[b].get(DIR_ENV), (
            f"{DIR_ENV} disagrees between {a} and {b}"
        )

    # k8s: each tier's bundle dir must live under a mounted volume, or a
    # container restart (the very event an incident precedes) loses the
    # evidence.
    for dep in (gw_dep, model_dep):
        pod = dep["spec"]["template"]["spec"]
        (container,) = pod["containers"]
        env = {e["name"]: str(e.get("value", "")) for e in container["env"]}
        mounts = [m["mountPath"] for m in container.get("volumeMounts", [])]
        assert any(env[DIR_ENV].startswith(m) for m in mounts), (
            f"{DIR_ENV}={env[DIR_ENV]} must live under a mounted volume"
        )


def test_ingest_envs_agree_across_k8s_and_compose():
    """The raw-bytes ingest wiring (ISSUE 20): KDLT_INGEST rides on BOTH
    tiers in BOTH deploy targets with agreeing values -- a gateway with
    the wire on and a model tier without it silently pays the fallback
    decode on every request -- plus the tier-local knobs: the model
    tier's decode pool and the gateway's hoisted fetch fan-out.  Every
    value must parse through the same resolvers the code uses."""
    from kubernetes_deep_learning_tpu.ops.preprocess import (
        DECODE_POOL_ENV,
        resolve_decode_pool,
    )
    from kubernetes_deep_learning_tpu.serving.gateway import (
        FETCH_CONCURRENCY_ENV,
        resolve_fetch_concurrency,
    )
    from kubernetes_deep_learning_tpu.serving.protocol import (
        INGEST_ENV,
        ingest_enabled,
    )

    k8s = os.path.join(DEPLOY, "k8s")
    (model_dep,) = _yaml_docs(os.path.join(k8s, "model-server-deployment.yaml"))
    (gw_dep,) = _yaml_docs(os.path.join(k8s, "gateway-deployment.yaml"))
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))

    def k8s_env(dep):
        (container,) = dep["spec"]["template"]["spec"]["containers"]
        return {e["name"]: str(e.get("value", "")) for e in container["env"]}

    def compose_env(svc):
        return {
            k: str(v)
            for k, v in compose["services"][svc]["environment"].items()
        }

    model_tier = {
        "k8s/model-server": k8s_env(model_dep),
        "compose/model-server": compose_env("model-server"),
        "compose/model-server-b": compose_env("model-server-b"),
    }
    gateway_tier = {
        "k8s/gateway": k8s_env(gw_dep),
        "compose/gateway": compose_env("gateway"),
    }
    for tier, var in (
        (model_tier, INGEST_ENV),
        (model_tier, DECODE_POOL_ENV),
        (gateway_tier, INGEST_ENV),
        (gateway_tier, FETCH_CONCURRENCY_ENV),
    ):
        values = {where: env.get(var) for where, env in tier.items()}
        assert all(v is not None for v in values.values()), (
            f"{var} missing from some copy of the tier: {values}"
        )
        assert len(set(values.values())) == 1, (
            f"{var} disagrees across the tier: {values}"
        )
    # The wire must be ON in both tiers (the negotiation handshake makes
    # a half-on deployment safe, but the shipped posture is on/on), and
    # every value must round-trip the production resolvers.
    ingest_value = model_tier["k8s/model-server"][INGEST_ENV]
    assert ingest_value == gateway_tier["k8s/gateway"][INGEST_ENV], (
        "the two tiers ship disagreeing KDLT_INGEST postures"
    )
    os.environ[INGEST_ENV] = ingest_value
    try:
        assert ingest_enabled() is True, (
            "deploys must not ship the ingest kill switch engaged"
        )
    finally:
        del os.environ[INGEST_ENV]
    pool = resolve_decode_pool(
        int(model_tier["k8s/model-server"][DECODE_POOL_ENV])
    )
    assert 1 <= pool <= 64, "decode pool wired to a nonsense width"
    fetchers = resolve_fetch_concurrency(
        int(gateway_tier["k8s/gateway"][FETCH_CONCURRENCY_ENV])
    )
    assert 1 <= fetchers <= 64, "fetch fan-out wired to a nonsense width"


def test_compose_services_reference_built_dockerfiles():
    compose = yaml.safe_load(_read(os.path.join(DEPLOY, "docker-compose.yaml")))
    for svc in compose["services"].values():
        build = svc.get("build")
        if isinstance(build, dict) and "dockerfile" in build:
            # Compose resolves context relative to the compose FILE, and the
            # dockerfile relative to that context.
            ctx = os.path.normpath(os.path.join(DEPLOY, build.get("context", ".")))
            path = os.path.join(ctx, build["dockerfile"])
            assert os.path.exists(path), f"compose references missing {path}"


def test_lockfile_consistent_with_constraints():
    """requirements.lock (the Pipfile.lock-equivalent transitive closure)
    must agree with constraints.txt's direct pins and cover the runtime
    dependency roots -- images install from the lock (deploy/*.dockerfile)."""
    import re

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def pins(path):
        out = {}
        for line in open(os.path.join(root, path)):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name, _, ver = line.partition("==")
            out[re.sub(r"[-_.]+", "-", name).lower()] = ver
        return out

    constraints = pins("constraints.txt")
    lock = pins("requirements.lock")
    assert len(lock) >= 40, f"suspiciously small lock ({len(lock)} pins)"
    for name, ver in constraints.items():
        assert name in lock, f"{name} pinned in constraints.txt but not locked"
        assert lock[name] == ver, (
            f"{name}: constraints.txt=={ver} but requirements.lock=={lock[name]}"
        )
    for direct in ("jax", "flax", "numpy", "msgpack", "pillow", "requests",
                   "optax", "grpcio", "protobuf", "gunicorn"):
        assert direct in lock, f"runtime root {direct} missing from lock"


def test_dockerfiles_install_from_lock():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for df in ("deploy/gateway.dockerfile", "deploy/model-server.dockerfile"):
        text = open(os.path.join(root, df)).read()
        assert "requirements.lock" in text, f"{df} does not use the lockfile"
        assert "-c requirements.lock" in text or "-c /tmp/requirements.lock" in text
