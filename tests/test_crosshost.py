"""Cross-host SPMD serving: 2 real processes, one frontend (VERDICT r1 #6).

Spawns two Python processes that join one jax runtime through
utils.distributed's env triplet (KDLT_COORDINATOR / _NUM_PROCESSES /
_PROCESS_ID), each with 4 virtual CPU devices, and drives ONE model sharded
over all 8 devices across both processes:

- worker test: leader predicts through parallel.crosshost.CrossHostForward,
  follower runs follower_loop(); logits must match a single-process forward
  of the same variables bit-for-tolerance.
- serving test: the leader runs a REAL ModelServer (HTTP, CrossHostEngine
  via engine_factory) and a client posts to it -- one frontend, model
  sharded across >= 2 processes.

These tests run each scenario in subprocesses (the parent pytest process
must stay out of the distributed runtime).
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import socket
import subprocess
import sys

import pytest


@contextlib.contextmanager
def _fleet_lock():
    """Cross-PROCESS serialization of multi-process fleet tests.

    VERDICT r4 weak-6: under a deliberately contended parallel run (two
    pytest invocations sharing this box's cores) a fleet worker was
    starved past Gloo's key-value rendezvous deadline, which is hardcoded
    in XLA's C++ (make_gloo_tcp_collectives exposes no timeout) -- so the
    fix must keep two fleets from ever competing for cores.  An in-process
    pytest lock cannot see the other invocation; an OS-level flock can.
    The jax coordination-service half of the deadline IS configurable:
    KDLT_DIST_INIT_TIMEOUT_S (utils/distributed.py).
    """
    path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "kdlt-fleet-tests.lock"
    )
    with open(path, "a+") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize(), "env triplet must trigger jax.distributed.initialize"
import jax
import numpy as np
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostForward
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
import jax.numpy as jnp

spec = register_spec(ModelSpec(
    name="xh-vit", family="vit-tiny", input_shape=(16, 16, 3),
    labels=("a", "b", "c"), preprocessing="tf",
))
variables = init_variables(spec, seed=7)  # same seed -> identical everywhere
mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(spec, mesh, variables, buckets=(4, 8))

mode = sys.argv[1]
if mode == "follower":
    rounds = xh.follower_loop()
    assert rounds == 2, f"expected 2 predict rounds, served {rounds}"
    print("FOLLOWER-OK", flush=True)
else:
    rng = np.random.default_rng(0)
    ref = jax.jit(build_forward(spec, dtype=jnp.bfloat16, fast=False))
    for batch in (8, 3):  # full bucket, then a padded partial batch
        images = rng.integers(0, 256, (batch, *spec.input_shape), np.uint8)
        got = xh.predict(images)
        want = np.asarray(ref(variables, images))
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    xh.shutdown()
    print("LEADER-OK", flush=True)
"""

_SERVING_WORKER = r"""
import os, sys, tempfile, threading, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import numpy as np
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import (
    CrossHostEngine, CrossHostForward,
)
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.export import artifact as art

spec = register_spec(ModelSpec(
    name="xh-serve", family="vit-tiny", input_shape=(16, 16, 3),
    labels=("a", "b", "c"), preprocessing="tf",
))
variables = init_variables(spec, seed=9)
mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(spec, mesh, variables, buckets=(8,))

if jax.process_index() != 0:
    xh.follower_loop()
    print("FOLLOWER-OK", flush=True)
    sys.exit(0)

# Leader: a real ModelServer over the cross-host engine.
root = tempfile.mkdtemp(prefix="kdlt-xh-")
art.save_artifact(art.version_dir(root, spec.name, 1), spec, variables, None, {})
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
server = ModelServer(
    root, port=0, host="127.0.0.1", use_batcher=False,
    engine_factory=lambda artifact, **kw: CrossHostEngine(artifact, xh, **kw),
)
server.warmup()
server.start()

import requests
from kubernetes_deep_learning_tpu.serving import protocol
rng = np.random.default_rng(1)
images = rng.integers(0, 256, (3, *spec.input_shape), np.uint8)
r = requests.post(
    f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict",
    data=protocol.encode_predict_request(images),
    headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
    timeout=60,
)
assert r.status_code == 200, r.text
logits, labels = protocol.decode_predict_response(r.content, r.headers["Content-Type"])
assert np.asarray(logits).shape == (3, 3)
assert labels == list(spec.labels)
server.shutdown()
xh.shutdown()
print("LEADER-OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_fleet_raw(worker_src: str, timeout: int = 420, extra_args=()):
    """Run leader+follower; returns [(returncode, output), ...] unasserted.

    The whole spawn-to-join span holds _fleet_lock so concurrent pytest
    invocations on a shared-core box run their fleets one at a time.
    """
    with _fleet_lock():
        port = _free_port()
        env_base = {
            **os.environ,
            "KDLT_COORDINATOR": f"127.0.0.1:{port}",
            "KDLT_NUM_PROCESSES": "2",
            # Generous coordination-service join window for contended CI
            # (honors an operator's own value when already set).
            "KDLT_DIST_INIT_TIMEOUT_S": os.environ.get(
                "KDLT_DIST_INIT_TIMEOUT_S", "120"
            ),
        }
        env_base.pop("JAX_PLATFORMS", None)
        procs = []
        for pid, mode in ((0, "leader"), (1, "follower")):
            env = {**env_base, "KDLT_PROCESS_ID": str(pid)}
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", worker_src, mode, *extra_args],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                    cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                )
            )
        results = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("cross-host fleet timed out")
            results.append((p.returncode, out))
        return results


def _run_fleet(worker_src: str, timeout: int = 420, extra_args=()):
    results = _run_fleet_raw(worker_src, timeout=timeout, extra_args=extra_args)
    for rc, out in results:
        assert rc == 0, f"worker failed:\n{out[-3000:]}"
    return [out for _, out in results]


_RELOAD_WORKER = r"""
import os, sys, tempfile
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import jax.numpy as jnp
import numpy as np
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostForward
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.export import artifact as art

spec = register_spec(ModelSpec(
    name="xh-reload", family="vit-tiny", input_shape=(16, 16, 3),
    labels=("a", "b", "c"), preprocessing="tf",
))
# A SHARED model root both processes can load versions from (the same
# assumption production makes: shared storage / identical image).
root = sys.argv[2]
v1 = init_variables(spec, seed=9)
v2 = init_variables(spec, seed=21)
if jax.process_index() == 0:
    art.save_artifact(art.version_dir(root, spec.name, 1), spec, v1, None, {})
    art.save_artifact(art.version_dir(root, spec.name, 2), spec, v2, None, {})
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("artifacts-written")

mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(
    spec, mesh, v1, buckets=(8,), model_root=root, model_name=spec.name,
)
xh.version = 1

mode = sys.argv[1]
if mode == "follower":
    rounds = xh.follower_loop()
    assert rounds == 2, f"expected 2 predict rounds across the reload, got {rounds}"
    print("FOLLOWER-OK", flush=True)
else:
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (5, *spec.input_shape), np.uint8)
    ref1 = jax.jit(build_forward(spec, dtype=jnp.bfloat16, fast=False))
    got1 = xh.predict(images)
    np.testing.assert_allclose(got1, np.asarray(ref1(v1, images)), rtol=2e-2, atol=2e-2)
    xh.reload(2)
    assert xh.version == 2
    got2 = xh.predict(images)
    np.testing.assert_allclose(got2, np.asarray(ref1(v2, images)), rtol=2e-2, atol=2e-2)
    assert np.abs(got1 - got2).max() > 1e-3, "reload served identical logits"
    xh.shutdown()
    print("LEADER-OK", flush=True)
"""

_DEATH_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import numpy as np
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostForward
from kubernetes_deep_learning_tpu.models import init_variables

spec = register_spec(ModelSpec(
    name="xh-death", family="vit-tiny", input_shape=(16, 16, 3),
    labels=("a", "b", "c"), preprocessing="tf",
))
variables = init_variables(spec, seed=3)
mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(spec, mesh, variables, buckets=(8,), round_timeout_s=20)

from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("fleet-up")

if sys.argv[1] == "follower":
    # Crash WITHOUT entering the loop: the leader's next round has a dead
    # peer and must not hang forever.
    os._exit(1)
rng = np.random.default_rng(0)
try:
    xh.predict(rng.integers(0, 256, (8, *spec.input_shape), np.uint8))
except BaseException as e:  # runtime error surfacing the dead peer: also OK
    # os._exit: the jax distributed atexit shutdown would itself raise on
    # the dead-peer barrier and mangle the exit code.
    print(f"LEADER-ERROR {type(e).__name__}", flush=True)
    os._exit(70)
print("LEADER-UNEXPECTED-SUCCESS", flush=True)
os._exit(1)
"""


def test_two_process_spmd_predict():
    leader_out, follower_out = _run_fleet(_WORKER)
    assert "LEADER-OK" in leader_out, leader_out[-2000:]
    assert "FOLLOWER-OK" in follower_out, follower_out[-2000:]


_ENCODED_WORKER = r"""
import io, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import numpy as np
from PIL import Image

from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.ops import preprocess
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostForward

spec = register_spec(ModelSpec(
    name="xh-enc", family="vit-tiny", input_shape=(16, 16, 3),
    labels=("a", "b", "c"), preprocessing="tf",
))
variables = init_variables(spec, seed=11)
mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(spec, mesh, variables, buckets=(4, 8))

mode = sys.argv[1]
if mode == "follower":
    rounds = xh.follower_loop()
    assert rounds == 2, f"expected 1 tensor + 1 encoded round, served {rounds}"
    print("FOLLOWER-OK", flush=True)
else:
    rng = np.random.default_rng(0)
    blobs = []
    for i in range(3):
        buf = io.BytesIO()
        Image.fromarray(
            rng.integers(0, 256, (16, 16, 3), np.uint8)
        ).save(buf, format="PNG")  # lossless at input size: decode is exact
        blobs.append(buf.getvalue())
    dec = preprocess.BatchDecoder(workers=2)
    decoded = dec.decode_batch(blobs, spec.input_shape[:2],
                               filter=spec.resize_filter)
    want = xh.predict(decoded)  # round 1: the legacy tensor wire
    # A corrupt blob must die at the LEADER, before any broadcast: the
    # follower's round count proves nothing reached the control channel.
    try:
        xh.predict_encoded_async([blobs[0], b"\xff\xd8\xffcorrupt"])
        raise SystemExit("corrupt blob must raise at the leader")
    except ValueError:
        pass
    handle, n = xh.predict_encoded_async(blobs)  # round 2: encoded wire
    got = np.asarray(handle)[:n]
    assert n == 3, n
    # Same bucket, same program, followers decoded the same bytes with
    # the same host kernels: the wires must agree bit for bit.
    np.testing.assert_array_equal(got, want)
    xh.shutdown()
    print("LEADER-OK", flush=True)
"""


def test_two_process_encoded_broadcast_matches_tensor_wire():
    """The raw-bytes ingest wire across a REAL 2-process fleet (GUIDE
    10q): the leader broadcasts packed encoded blobs, every follower
    decodes locally, and the round's logits are bit-identical to the
    legacy tensor-wire round on the same pixels; a corrupt blob raises at
    the leader without consuming a fleet round."""
    leader_out, follower_out = _run_fleet(_ENCODED_WORKER)
    assert "LEADER-OK" in leader_out, leader_out[-2000:]
    assert "FOLLOWER-OK" in follower_out, follower_out[-2000:]


def test_reload_round_trip():
    """Fleet-wide hot version reload: v1 predicts, RELOAD broadcast, v2
    predicts -- all against single-process references (VERDICT r2 #5)."""
    import tempfile

    root = tempfile.mkdtemp(prefix="kdlt-xh-reload-")
    leader_out, follower_out = _run_fleet(_RELOAD_WORKER, extra_args=[root])
    assert "LEADER-OK" in leader_out, leader_out[-2000:]
    assert "FOLLOWER-OK" in follower_out, follower_out[-2000:]


def test_follower_death_does_not_hang_leader():
    """Crash semantics: a dead follower must end the leader's round with
    exit 70 (watchdog or surfaced runtime error), never an indefinite
    hang -- k8s then restarts the gang (VERDICT r2 #5)."""
    leader, follower = _run_fleet_raw(_DEATH_WORKER, timeout=180)
    (l_rc, l_out), (f_rc, f_out) = leader, follower
    assert f_rc == 1, f_out[-1000:]
    assert l_rc == 70, f"leader rc {l_rc}:\n{l_out[-2000:]}"


def test_two_process_http_serving():
    leader_out, follower_out = _run_fleet(_SERVING_WORKER)
    assert "LEADER-OK" in leader_out, leader_out[-2000:]
    assert "FOLLOWER-OK" in follower_out, follower_out[-2000:]


_WATCHER_RELOAD_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import jax.numpy as jnp
import numpy as np
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import (
    CrossHostEngine, CrossHostForward,
)
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.export import artifact as art

spec = register_spec(ModelSpec(
    name="xh-watch", family="vit-tiny", input_shape=(16, 16, 3),
    labels=("a", "b", "c"), preprocessing="tf",
))
root = sys.argv[2]
v1 = init_variables(spec, seed=9)
v2 = init_variables(spec, seed=33)
if jax.process_index() == 0:
    art.save_artifact(art.version_dir(root, spec.name, 1), spec, v1, None, {})
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("v1-written")

mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(
    spec, mesh, v1, buckets=(8,), model_root=root, model_name=spec.name,
)
xh.version = 1

if jax.process_index() != 0:
    rounds = xh.follower_loop()
    print("FOLLOWER-OK", rounds, flush=True)
    sys.exit(0)

# Leader: REAL ModelServer + the standard version watcher; dropping a v2
# dir must hot-swap the whole fleet through CrossHostEngine's RELOAD.
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
server = ModelServer(
    root, port=0, host="127.0.0.1", use_batcher=False,
    engine_factory=lambda artifact, **kw: CrossHostEngine(artifact, xh, **kw),
)
server.warmup()
server.start()

import requests
from kubernetes_deep_learning_tpu.serving import protocol
rng = np.random.default_rng(1)
images = rng.integers(0, 256, (3, *spec.input_shape), np.uint8)

def predict():
    r = requests.post(
        f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict",
        data=protocol.encode_predict_request(images),
        headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
        timeout=60,
    )
    assert r.status_code == 200, r.text
    logits, _ = protocol.decode_predict_response(r.content, r.headers["Content-Type"])
    return np.asarray(logits)

before = predict()
art.save_artifact(art.version_dir(root, spec.name, 2), spec, v2, None, {})
updated = server.poll_versions()  # the watcher's scan, invoked directly
assert updated == [f"{spec.name} v2"], updated
assert xh.version == 2
after = predict()
# Not just "changed": the post-reload logits must MATCH a single-process
# v2 reference, or a reload that installs wrong weights would pass.
ref = jax.jit(build_forward(spec, dtype=jnp.bfloat16, fast=False))
np.testing.assert_allclose(after, np.asarray(ref(v2, images)), rtol=2e-2, atol=2e-2)
assert np.abs(before - after).max() > 1e-3, "watcher reload served same logits"
server.shutdown()
xh.shutdown()
print("LEADER-OK", flush=True)
"""


_FAST_WORKER = r"""
import functools, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import jax.numpy as jnp
import numpy as np
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostForward
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.models import xception_fast

spec = register_spec(ModelSpec(
    name="xh-fast", family="xception", input_shape=(96, 96, 3),
    labels=("a", "b", "c", "d"), preprocessing="tf",
))
# Interpret-mode Pallas stands in for Mosaic on CPU (same stand-in as
# tests/test_sharded_serving.py) -- on EVERY process, so the follower's
# lazy fast build compiles the same interpreted program the leader probed.
xception_fast.build_fast_forward = functools.partial(
    xception_fast.build_fast_forward, interpret=True
)
variables = init_variables(spec, seed=5)
mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(spec, mesh, variables, buckets=(8,), fast=True)

if sys.argv[1] == "follower":
    rounds = xh.follower_loop()
    assert rounds == 2, f"expected 2 fast predict rounds, served {rounds}"
    print("FOLLOWER-OK", flush=True)
else:
    assert xh.resolve_mode() == "fast", xh.mode
    assert not xh.fast_degraded
    rng = np.random.default_rng(0)
    ref = jax.jit(build_forward(spec, dtype=jnp.bfloat16, fast=False))
    for batch in (8, 3):  # full bucket, then a padded partial batch
        images = rng.integers(0, 256, (batch, *spec.input_shape), np.uint8)
        got = xh.predict(images)
        want = np.asarray(ref(variables, images))
        # 2e-2: the pallas interpreter's bf16 accumulation rounds slightly
        # differently across jax versions (same spread as
        # tests/test_fused_sepconv.py; measured 1.57e-2 on 0.4.x).
        rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
        assert rel < 2e-2, f"fast cross-host round diverges from flax: {rel:.2e}"
    xh.shutdown()
    print("LEADER-OK", flush=True)
"""

_DEGRADE_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import jax.numpy as jnp
import numpy as np
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostForward
from kubernetes_deep_learning_tpu.models import build_forward, init_variables

spec = register_spec(ModelSpec(
    name="xh-degrade", family="xception", input_shape=(96, 96, 3),
    labels=("a", "b", "c", "d"), preprocessing="tf",
))
# fast FORCED but no interpret stand-in: the leader's AOT probe hits the
# real "no Mosaic on CPU" lowering failure -- the stand-in for a Mosaic
# legality regression on TPU -- and must degrade the WHOLE fleet to exact
# rounds; the followers never trace the broken program.
variables = init_variables(spec, seed=6)
mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(spec, mesh, variables, buckets=(8,), fast=True)
assert xh._fast_possible  # forced: the probe, not static resolution, degrades

if sys.argv[1] == "follower":
    rounds = xh.follower_loop()
    assert rounds == 1, f"expected 1 exact predict round, served {rounds}"
    print("FOLLOWER-OK", flush=True)
else:
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (5, *spec.input_shape), np.uint8)
    got = xh.predict(images)  # resolves mode -> degrade -> exact round
    assert xh.fast_degraded and xh.mode == "exact", (xh.fast_degraded, xh.mode)
    ref = jax.jit(build_forward(spec, dtype=jnp.bfloat16, fast=False))
    np.testing.assert_allclose(
        got, np.asarray(ref(variables, images)), rtol=2e-2, atol=2e-2
    )
    xh.shutdown()
    print("LEADER-OK", flush=True)
"""


def test_fast_path_rounds_match_flax():
    """The fused fast path carried into cross-host serving (VERDICT r3 #3):
    a 2-process fleet resolves mode "fast", broadcasts PREDICT_FAST, runs
    the fused program under shard_map on every process, and the logits
    match the exact flax graph."""
    leader_out, follower_out = _run_fleet(_FAST_WORKER, timeout=600)
    assert "LEADER-OK" in leader_out, leader_out[-2000:]
    assert "FOLLOWER-OK" in follower_out, follower_out[-2000:]


def test_fast_compile_failure_degrades_fleet_wide():
    """A fused-path compile failure must be a FLEET-WIDE decision: the
    leader's AOT probe fails, every subsequent round broadcasts exact, and
    no follower ever traces the broken program (VERDICT r3 #3: 'a follower
    compile failure must not wedge the fleet')."""
    leader_out, follower_out = _run_fleet(_DEGRADE_WORKER, timeout=600)
    assert "LEADER-OK" in leader_out, leader_out[-2000:]
    assert "FOLLOWER-OK" in follower_out, follower_out[-2000:]


def test_version_watcher_drives_fleet_reload():
    """End to end through the REAL server reload flow: a higher version
    dir makes poll_versions construct a fresh CrossHostEngine whose init
    broadcasts RELOAD to the followers (VERDICT r2 #5 'through the
    standard version watcher')."""
    import tempfile

    root = tempfile.mkdtemp(prefix="kdlt-xh-watch-")
    leader_out, follower_out = _run_fleet(_WATCHER_RELOAD_WORKER, extra_args=[root])
    assert "LEADER-OK" in leader_out, leader_out[-2000:]
    assert "FOLLOWER-OK" in follower_out, follower_out[-2000:]
