"""Round-trip test for the Keras .h5 importer.

We cannot ship the reference's trained artifact (reference guide.md:176), so
the test synthesizes an .h5 in the exact Keras-file layout (including the
auto-named residual conv/BN and head Dense layers) from our own random
variables, imports it, and checks the imported model reproduces the original
forward pass bit-for-bit.
"""

import jax
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.models.keras_import import load_keras_h5


def _flax_to_keras_h5(path, variables):
    """Write flax Xception variables as a Keras-layout .h5 file."""
    import h5py

    params = variables["params"]
    stats = variables["batch_stats"]

    def keras_layers():
        auto_conv = 0
        auto_bn = 0
        res_map = {
            "block2_res": 0, "block3_res": 1, "block4_res": 2, "block13_res": 3,
        }
        for name, p in sorted(params.items()):
            if name == "head":
                continue
            if name.endswith("_res_conv"):
                n = res_map[name[: -len("_conv")]]
                kname = "conv2d" if n == 0 else f"conv2d_{n}"
                yield kname, {"kernel": p["kernel"]}
            elif name.endswith("_res_bn"):
                n = res_map[name[: -len("_bn")]]
                kname = "batch_normalization" if n == 0 else f"batch_normalization_{n}"
                yield kname, _bn_weights(p, stats[name])
            elif "sepconv" in name and not name.endswith("_bn"):
                dw = np.transpose(np.asarray(p["depthwise"]["kernel"]), (0, 1, 3, 2))
                yield name, {
                    "depthwise_kernel": dw,
                    "pointwise_kernel": np.asarray(p["pointwise"]["kernel"]),
                }
            elif name.endswith("_bn"):
                yield name, _bn_weights(p, stats[name])
            else:
                yield name, {"kernel": np.asarray(p["kernel"])}
        head = params["head"]
        hidden = sorted(k for k in head if k.startswith("hidden_"))
        for i, h in enumerate(hidden):
            yield f"dense_{5 + i}", {
                "kernel": np.asarray(head[h]["kernel"]),
                "bias": np.asarray(head[h]["bias"]),
            }
        yield f"dense_{5 + len(hidden)}", {
            "kernel": np.asarray(head["logits"]["kernel"]),
            "bias": np.asarray(head["logits"]["bias"]),
        }

    def _bn_weights(p, s):
        return {
            "gamma": np.asarray(p["scale"]),
            "beta": np.asarray(p["bias"]),
            "moving_mean": np.asarray(s["mean"]),
            "moving_variance": np.asarray(s["var"]),
        }

    with h5py.File(path, "w") as f:
        mw = f.create_group("model_weights")
        base = mw.create_group("xception")  # nested-submodel layout
        for lname, weights in keras_layers():
            grp = (mw if lname.startswith("dense") else base).create_group(lname)
            inner = grp.create_group(lname)
            for wname, arr in weights.items():
                inner.create_dataset(f"{wname}:0", data=np.asarray(arr))


@pytest.fixture(scope="module")
def h5_spec():
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

    return register_spec(
        ModelSpec(
            name="h5-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
            head_hidden=(16,),
        )
    )


def test_h5_roundtrip_bitexact(tmp_path, h5_spec):
    variables = init_variables(h5_spec, seed=42)
    path = tmp_path / "model.h5"
    _flax_to_keras_h5(path, variables)

    imported = load_keras_h5(h5_spec, str(path))

    fwd = jax.jit(build_forward(h5_spec, dtype=None))
    rng = np.random.default_rng(7)
    x = rng.integers(0, 256, size=(2, *h5_spec.input_shape), dtype=np.uint8)
    a = np.asarray(fwd(variables, x))
    b = np.asarray(fwd(imported, x))
    np.testing.assert_array_equal(a, b)


def test_h5_import_rejects_wrong_head(tmp_path, h5_spec):
    import dataclasses

    variables = init_variables(h5_spec, seed=0)
    path = tmp_path / "model.h5"
    _flax_to_keras_h5(path, variables)
    bad_spec = dataclasses.replace(h5_spec, head_hidden=(32,))
    with pytest.raises(ValueError, match="head hidden"):
        load_keras_h5(bad_spec, str(path))
