"""Round-trip test for the Keras .h5 importer.

We cannot ship the reference's trained artifact (reference guide.md:176), so
the test synthesizes an .h5 in the exact Keras-file layout (including the
auto-named residual conv/BN and head Dense layers) from our own random
variables, imports it, and checks the imported model reproduces the original
forward pass bit-for-bit.
"""

import jax
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.models.keras_import import load_keras_h5
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec


def _flax_to_keras_h5(path, variables):
    """Write flax Xception variables as a Keras-layout .h5 file."""
    import h5py

    params = variables["params"]
    stats = variables["batch_stats"]

    def keras_layers():
        auto_conv = 0
        auto_bn = 0
        res_map = {
            "block2_res": 0, "block3_res": 1, "block4_res": 2, "block13_res": 3,
        }
        for name, p in sorted(params.items()):
            if name == "head":
                continue
            if name.endswith("_res_conv"):
                n = res_map[name[: -len("_conv")]]
                kname = "conv2d" if n == 0 else f"conv2d_{n}"
                yield kname, {"kernel": p["kernel"]}
            elif name.endswith("_res_bn"):
                n = res_map[name[: -len("_bn")]]
                kname = "batch_normalization" if n == 0 else f"batch_normalization_{n}"
                yield kname, _bn_weights(p, stats[name])
            elif "sepconv" in name and not name.endswith("_bn"):
                dw = np.transpose(np.asarray(p["depthwise"]["kernel"]), (0, 1, 3, 2))
                yield name, {
                    "depthwise_kernel": dw,
                    "pointwise_kernel": np.asarray(p["pointwise"]["kernel"]),
                }
            elif name.endswith("_bn"):
                yield name, _bn_weights(p, stats[name])
            else:
                yield name, {"kernel": np.asarray(p["kernel"])}
        head = params["head"]
        hidden = sorted(k for k in head if k.startswith("hidden_"))
        for i, h in enumerate(hidden):
            yield f"dense_{5 + i}", {
                "kernel": np.asarray(head[h]["kernel"]),
                "bias": np.asarray(head[h]["bias"]),
            }
        yield f"dense_{5 + len(hidden)}", {
            "kernel": np.asarray(head["logits"]["kernel"]),
            "bias": np.asarray(head["logits"]["bias"]),
        }

    def _bn_weights(p, s):
        return {
            "gamma": np.asarray(p["scale"]),
            "beta": np.asarray(p["bias"]),
            "moving_mean": np.asarray(s["mean"]),
            "moving_variance": np.asarray(s["var"]),
        }

    with h5py.File(path, "w") as f:
        mw = f.create_group("model_weights")
        base = mw.create_group("xception")  # nested-submodel layout
        for lname, weights in keras_layers():
            grp = (mw if lname.startswith("dense") else base).create_group(lname)
            inner = grp.create_group(lname)
            for wname, arr in weights.items():
                inner.create_dataset(f"{wname}:0", data=np.asarray(arr))


@pytest.fixture(scope="module")
def h5_spec():
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

    return register_spec(
        ModelSpec(
            name="h5-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
            head_hidden=(16,),
        )
    )


def test_h5_roundtrip_bitexact(tmp_path, h5_spec):
    variables = init_variables(h5_spec, seed=42)
    path = tmp_path / "model.h5"
    _flax_to_keras_h5(path, variables)

    imported = load_keras_h5(h5_spec, str(path))

    fwd = jax.jit(build_forward(h5_spec, dtype=None))
    rng = np.random.default_rng(7)
    x = rng.integers(0, 256, size=(2, *h5_spec.input_shape), dtype=np.uint8)
    a = np.asarray(fwd(variables, x))
    b = np.asarray(fwd(imported, x))
    np.testing.assert_array_equal(a, b)


def test_h5_import_rejects_wrong_head(tmp_path, h5_spec):
    import dataclasses

    variables = init_variables(h5_spec, seed=0)
    path = tmp_path / "model.h5"
    _flax_to_keras_h5(path, variables)
    bad_spec = dataclasses.replace(h5_spec, head_hidden=(32,))
    with pytest.raises(ValueError, match="head hidden"):
        load_keras_h5(bad_spec, str(path))


def _flax_resnet_to_keras_h5(path, variables):
    """Write flax ResNet50 variables as a keras.applications-style .h5."""
    import h5py

    params, stats = variables["params"], variables["batch_stats"]

    def conv_entry(p):
        return {"kernel": p["kernel"], "bias": p["bias"]}

    def bn_entry(p, s):
        return {
            "gamma": p["scale"], "beta": p["bias"],
            "moving_mean": s["mean"], "moving_variance": s["var"],
        }

    entries = {
        "conv1_conv": conv_entry(params["conv1_conv"]),
        "conv1_bn": bn_entry(params["conv1_bn"], stats["conv1_bn"]),
        "predictions": {
            "kernel": params["head"]["logits"]["kernel"],
            "bias": params["head"]["logits"]["bias"],
        },
    }
    for block, sub in params.items():
        if "_block" not in block:
            continue
        for k in ("0", "1", "2", "3"):
            if f"{k}_conv" in sub:
                entries[f"{block}_{k}_conv"] = conv_entry(sub[f"{k}_conv"])
            if f"{k}_bn" in sub:
                entries[f"{block}_{k}_bn"] = bn_entry(
                    sub[f"{k}_bn"], stats[block][f"{k}_bn"]
                )
    with h5py.File(path, "w") as f:
        root = f.create_group("model_weights")
        for layer, weights in entries.items():
            g = root.create_group(layer)
            for wname, arr in weights.items():
                g.create_dataset(f"{wname}:0", data=np.asarray(arr))


def test_resnet50_h5_roundtrip_bitexact(tmp_path):
    import jax

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.models.keras_import import load_keras_h5

    spec = register_spec(
        ModelSpec(
            name="h5-resnet",
            family="resnet50",
            input_shape=(64, 64, 3),
            labels=("a", "b", "c"),
            preprocessing="caffe",
        )
    )
    variables = init_variables(spec, seed=3)
    path = tmp_path / "resnet.h5"
    _flax_resnet_to_keras_h5(str(path), variables)
    imported = load_keras_h5(spec, str(path))

    flat_a, tree_a = jax.tree_util.tree_flatten(variables)
    flat_b, tree_b = jax.tree_util.tree_flatten(imported)
    assert tree_a == tree_b
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    fwd = build_forward(spec, dtype=None)
    x = np.random.default_rng(0).integers(0, 256, (2, 64, 64, 3), np.uint8)
    np.testing.assert_allclose(
        np.asarray(fwd(variables, x)), np.asarray(fwd(imported, x)), atol=0
    )


def _keras_eff_block_names(variant):
    """Flat block index -> keras 'block{stage}{letter}' name, creation order."""
    from kubernetes_deep_learning_tpu.models.efficientnet import (
        _BASE_BLOCKS,
        SCALING,
        round_repeats,
    )

    _, depth, _ = SCALING[variant]
    names = []
    for stage, (_, _, repeats, _, _) in enumerate(_BASE_BLOCKS, start=1):
        for rep in range(round_repeats(repeats, depth)):
            names.append(f"block{stage}{chr(ord('a') + rep)}")
    return names


def _flax_efficientnet_to_keras_h5(path, variant, variables):
    """Write flax EfficientNet variables as a keras.applications-style .h5."""
    import h5py

    params, stats = variables["params"], variables["batch_stats"]

    def bn_entry(p, s):
        return {
            "gamma": p["scale"], "beta": p["bias"],
            "moving_mean": s["mean"], "moving_variance": s["var"],
        }

    entries = {
        "stem_conv": {"kernel": params["stem_conv"]["kernel"]},
        "stem_bn": bn_entry(params["stem_bn"], stats["stem_bn"]),
        "top_conv": {"kernel": params["top_conv"]["kernel"]},
        "top_bn": bn_entry(params["top_bn"], stats["top_bn"]),
    }
    head = params["head"]
    hidden = sorted(k for k in head if k.startswith("hidden_"))
    if hidden:  # fine-tuned head: auto-named Dense chain, last one = logits
        for i, h in enumerate(hidden):
            entries[f"dense_{i}" if i else "dense"] = {
                "kernel": head[h]["kernel"], "bias": head[h]["bias"]
            }
        entries[f"dense_{len(hidden)}"] = {
            "kernel": head["logits"]["kernel"], "bias": head["logits"]["bias"]
        }
    else:  # stock ImageNet head
        entries["predictions"] = {
            "kernel": head["logits"]["kernel"], "bias": head["logits"]["bias"]
        }
    knames = _keras_eff_block_names(variant)
    for i, kname in enumerate(knames):
        bp, bs = params[f"block{i}"], stats[f"block{i}"]
        if "expand_conv" in bp:
            entries[f"{kname}_expand_conv"] = {"kernel": bp["expand_conv"]["kernel"]}
            entries[f"{kname}_expand_bn"] = bn_entry(bp["expand_bn"], bs["expand_bn"])
        # keras stores depthwise kernels (kh, kw, c, 1); flax (kh, kw, 1, c)
        entries[f"{kname}_dwconv"] = {
            "depthwise_kernel": np.transpose(np.asarray(bp["dwconv"]["kernel"]), (0, 1, 3, 2))
        }
        entries[f"{kname}_bn"] = bn_entry(bp["dw_bn"], bs["dw_bn"])
        entries[f"{kname}_se_reduce"] = {
            "kernel": bp["se"]["reduce"]["kernel"], "bias": bp["se"]["reduce"]["bias"]
        }
        entries[f"{kname}_se_expand"] = {
            "kernel": bp["se"]["expand"]["kernel"], "bias": bp["se"]["expand"]["bias"]
        }
        entries[f"{kname}_project_conv"] = {"kernel": bp["project_conv"]["kernel"]}
        entries[f"{kname}_project_bn"] = bn_entry(bp["project_bn"], bs["project_bn"])
    with h5py.File(path, "w") as f:
        root = f.create_group("model_weights")
        for layer, weights in entries.items():
            g = root.create_group(layer)
            for wname, arr in weights.items():
                g.create_dataset(f"{wname}:0", data=np.asarray(arr))


@pytest.mark.parametrize("variant", ["b0", "b3"])
def test_efficientnet_h5_roundtrip_bitexact(tmp_path, variant):
    # b0 also covers a fine-tuned hidden head (the clothing-model shape);
    # b3 covers the served BASELINE config-4 family's deeper repeat counts.
    spec = register_spec(
        ModelSpec(
            name=f"h5-eff-{variant}",
            family=f"efficientnet-{variant}",
            input_shape=(64, 64, 3),
            labels=("a", "b", "c"),
            preprocessing="torch",
            head_hidden=(16,) if variant == "b0" else (),
        )
    )
    variables = init_variables(spec, seed=5)
    path = tmp_path / "eff.h5"
    _flax_efficientnet_to_keras_h5(str(path), variant, variables)
    imported = load_keras_h5(spec, str(path))

    flat_a, tree_a = jax.tree_util.tree_flatten(variables)
    flat_b, tree_b = jax.tree_util.tree_flatten(imported)
    assert tree_a == tree_b
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    fwd = jax.jit(build_forward(spec, dtype=None))
    x = np.random.default_rng(1).integers(0, 256, (2, 64, 64, 3), np.uint8)
    np.testing.assert_array_equal(
        np.asarray(fwd(variables, x)), np.asarray(fwd(imported, x))
    )


def test_efficientnet_h5_rejects_non_torch_preprocessing(tmp_path):
    """A keras Normalization layer in the .h5 demands spec.preprocessing='torch'."""
    import h5py

    spec = register_spec(
        ModelSpec(
            name="h5-eff-badpre",
            family="efficientnet-b0",
            input_shape=(64, 64, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",  # wrong: keras EfficientNet normalizes in-model
        )
    )
    variables = init_variables(spec, seed=0)
    path = tmp_path / "eff.h5"
    _flax_efficientnet_to_keras_h5(str(path), "b0", variables)
    with h5py.File(path, "a") as f:
        g = f["model_weights"].create_group("normalization")
        g.create_dataset("mean:0", data=np.array([0.485, 0.456, 0.406]))
        g.create_dataset("variance:0", data=np.array([0.052, 0.050, 0.051]))
    with pytest.raises(ValueError, match="preprocessing"):
        load_keras_h5(spec, str(path))


def test_resnet50_h5_rejects_wrong_head(tmp_path):
    from kubernetes_deep_learning_tpu.models import init_variables
    from kubernetes_deep_learning_tpu.models.keras_import import load_keras_h5

    spec = register_spec(
        ModelSpec(
            name="h5-resnet-wrong",
            family="resnet50",
            input_shape=(64, 64, 3),
            labels=("a", "b", "c"),
            preprocessing="caffe",
        )
    )
    donor = register_spec(
        ModelSpec(
            name="h5-resnet-donor",
            family="resnet50",
            input_shape=(64, 64, 3),
            labels=("a", "b"),  # 2-class head, spec expects 3
            preprocessing="caffe",
        )
    )
    path = tmp_path / "wrong.h5"
    _flax_resnet_to_keras_h5(str(path), init_variables(donor, seed=0))
    with pytest.raises(ValueError, match="logits width"):
        load_keras_h5(spec, str(path))
