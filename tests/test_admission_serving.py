"""Admission control wired through the REAL serving tiers (stub backend).

The acceptance surface of the admission subsystem, all device-free:
deadline-exhausted rejection at both tiers, shed-vs-accept under a
saturated stub engine, the gateway circuit breaker's open/half-open/close
transitions, graceful drain completing in-flight work, and the deadline
budget observably propagating gateway -> model tier -> batcher via the
kdlt_admission_* metrics.
"""

from __future__ import annotations

import re
import threading
from functools import partial
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
from kubernetes_deep_learning_tpu.serving import protocol
from kubernetes_deep_learning_tpu.serving.admission import DEADLINE_HEADER
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer


def _metric(text: str, name: str, **labels: str) -> float:
    """First sample of ``name`` whose label set includes ``labels``."""
    for m in re.finditer(rf"^{re.escape(name)}(\{{[^}}]*\}})? (\S+)$", text, re.M):
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1) or ""))
        if all(got.get(k) == v for k, v in labels.items()):
            return float(m.group(2))
    raise AssertionError(f"no sample {name} with {labels} in:\n{text}")


def _make_stub_server(name: str, tmp_path, device_ms: float = 0.0, **kw):
    spec = register_spec(
        ModelSpec(
            name=name,
            family="xception",  # never instantiated by StubEngine
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )
    root = tmp_path / "models"
    art.save_artifact(
        art.version_dir(str(root), spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        str(root), port=0, buckets=kw.pop("buckets", (1, 2, 4, 8)),
        max_delay_ms=1.0, host="127.0.0.1",
        engine_factory=lambda a, **ekw: StubEngine(
            a, device_ms_per_batch=device_ms, **ekw
        ),
        **kw,
    )
    server.warmup()
    server.start()
    return spec, server


def _post_predict(spec, server, deadline_ms=None, n=1, timeout=30.0):
    import requests

    img = np.zeros((n, *spec.input_shape), np.uint8)
    headers = {"Content-Type": protocol.MSGPACK_CONTENT_TYPE}
    if deadline_ms is not None:
        headers[DEADLINE_HEADER] = str(deadline_ms)
    return requests.post(
        f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict",
        data=protocol.encode_predict_request(img),
        headers=headers,
        timeout=timeout,
    )


# --- deadline-exhausted rejection at both tiers ----------------------------


def test_model_tier_rejects_exhausted_deadline(tmp_path):
    spec, server = _make_stub_server("adm-exhaust", tmp_path)
    try:
        r = _post_predict(spec, server, deadline_ms=0)
        assert r.status_code == 504
        assert r.json()["shed_reason"] == "deadline_exhausted"
        # Rejected BEFORE the engine: no image was executed.
        import requests

        metrics = requests.get(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).text
        assert _metric(
            metrics, "kdlt_admission_shed_total",
            tier="model-server", shed_reason="deadline_exhausted",
        ) == 1.0
        assert _metric(metrics, "kdlt_engine_images_total") == 0.0
        # A healthy budget on the same server still serves.
        assert _post_predict(spec, server, deadline_ms=10_000).status_code == 200
    finally:
        server.shutdown()


def test_gateway_rejects_exhausted_deadline_without_upstream_call(tmp_path):
    import requests

    # Upstream host is a dead port: if the gateway consulted the model tier
    # at all this would be a 502, not the admission 504.
    gw = Gateway(serving_host="127.0.0.1:9", model="nope", port=0, host="127.0.0.1")
    gw.start()
    try:
        r = requests.post(
            f"http://127.0.0.1:{gw.port}/predict",
            json={"url": "http://127.0.0.1:1/x.png"},
            headers={DEADLINE_HEADER: "0"},
            timeout=10,
        )
        assert r.status_code == 504
        assert r.json()["shed_reason"] == "deadline_exhausted"
    finally:
        gw.shutdown()


def test_shed_keeps_pooled_keepalive_connection_usable(tmp_path):
    # admit() sheds BEFORE the request body is read; on HTTP/1.1 keep-alive
    # the unread msgpack payload would be parsed as the next request line,
    # desyncing the pooled connection and failing innocent follow-on
    # requests with garbage 400s -- exactly in the overload regime the
    # subsystem targets.  The handler must drain (or close over) it.
    import requests

    spec, server = _make_stub_server("adm-keepalive", tmp_path)
    try:
        session = requests.Session()  # one pooled connection, like the gateway
        url = f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict"
        body = protocol.encode_predict_request(
            np.zeros((1, *spec.input_shape), np.uint8)
        )
        headers = {"Content-Type": protocol.MSGPACK_CONTENT_TYPE}
        r = session.post(
            url, data=body, headers={**headers, DEADLINE_HEADER: "0"}, timeout=10
        )
        assert r.status_code == 504
        for _ in range(3):  # the SAME pooled connection keeps working
            r = session.post(
                url, data=body,
                headers={**headers, DEADLINE_HEADER: "10000"}, timeout=10,
            )
            assert r.status_code == 200, (r.status_code, r.text[:200])
    finally:
        server.shutdown()


# --- shed vs accept under a saturated stub engine --------------------------


def test_saturated_stub_sheds_excess_and_serves_the_rest(tmp_path, monkeypatch):
    # 2 concurrency slots (floor = 2 x max bucket), 150 ms serial service:
    # 8 concurrent requests with a 1 s budget cannot all fit -- the excess
    # must shed with a Retry-After while the admitted ones complete.
    monkeypatch.setenv("KDLT_ADMISSION_MAX_CONCURRENCY", "2")
    monkeypatch.setenv("KDLT_ADMISSION_INITIAL_CONCURRENCY", "2")
    spec, server = _make_stub_server(
        "adm-saturated", tmp_path, device_ms=150.0, buckets=(1,)
    )
    try:
        results: list = [None] * 8

        def hit(i):
            results[i] = _post_predict(spec, server, deadline_ms=1000)

        threads = [threading.Thread(target=hit, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        statuses = [r.status_code for r in results]
        assert statuses.count(200) >= 1, statuses
        shed = [r for r in results if r.status_code in (503, 504)]
        assert shed, statuses
        for r in shed:
            assert "Retry-After" in r.headers or "shed_reason" in r.json()
        import requests

        metrics = requests.get(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).text
        total_shed = sum(
            _metric(metrics, "kdlt_admission_shed_total",
                    tier="model-server", shed_reason=reason)
            for reason in ("queue_timeout", "queue_full", "deadline_exhausted")
        )
        admitted = _metric(
            metrics, "kdlt_admission_admitted_total", tier="model-server"
        )
        assert admitted >= 1
        assert total_shed + admitted >= 8 - statuses.count(-1)
    finally:
        server.shutdown()


# --- circuit breaker transitions through the gateway -----------------------


def test_gateway_breaker_open_half_open_close(tmp_path):
    import requests as requests_lib

    from kubernetes_deep_learning_tpu.serving.admission import CircuitBreaker
    from kubernetes_deep_learning_tpu.serving.admission import breaker as bmod
    from kubernetes_deep_learning_tpu.serving.gateway import UpstreamError

    class FakeClock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = FakeClock()
    gw = Gateway(serving_host="127.0.0.1:9", model="m", port=0, bind=False)
    gw.breaker = CircuitBreaker(
        failure_threshold=2, reset_timeout_s=5.0, half_open_probes=1, clock=clock
    )
    calls = {"n": 0}

    def dead_post(*a, **kw):
        calls["n"] += 1
        raise requests_lib.ConnectionError("down")

    session = gw._session()
    session.post = dead_post
    img = np.zeros((1, 32, 32, 3), np.uint8)
    # Two consecutive upstream failures trip the breaker (a connection
    # error fails straight through, one recorded failure per call).
    for _ in range(2):
        with pytest.raises(UpstreamError):
            gw._predict_batch(img)
    assert gw.breaker.state == bmod.OPEN
    # OPEN: refused locally, upstream never dialed, Retry-After = cool-down.
    before = calls["n"]
    with pytest.raises(UpstreamError) as exc:
        gw._predict_batch(img)
    assert "breaker" in str(exc.value)
    assert exc.value.http_status == 503
    assert exc.value.retry_after_s == pytest.approx(5.0)
    assert calls["n"] == before

    # Cool-down elapsed -> HALF_OPEN: the probe goes through to a healthy
    # upstream and closes the breaker.
    clock.t = 6.0
    rows = np.arange(3, dtype=np.float32)[None]

    class Ok:
        status_code = 200
        content, headers_ct = protocol.encode_predict_response(
            rows, ("a", "b", "c"), protocol.MSGPACK_CONTENT_TYPE
        )
        headers = {"Content-Type": headers_ct}
        text = ""

    session.post = lambda *a, **kw: Ok()
    logits, labels = gw._predict_batch(img)
    assert gw.breaker.state == bmod.CLOSED
    assert list(labels) == ["a", "b", "c"]
    # And the shed was accounted.
    assert (
        'kdlt_admission_shed_total{tier="gateway",shed_reason="breaker_open"} 1'
        in gw.registry.render()
    )


def test_gateway_503_retry_skipped_without_budget_for_it():
    # The one-shot 503 retry sleeps UPSTREAM_RETRY_BACKOFF_S; a nearly-
    # expired request must not burn its last budget sleeping and re-posting
    # work that cannot finish in time.
    from kubernetes_deep_learning_tpu.serving.admission import Deadline
    from kubernetes_deep_learning_tpu.serving.gateway import UpstreamError

    gw = Gateway(serving_host="127.0.0.1:9", model="m", port=0, bind=False)
    calls = {"n": 0}

    class Overloaded:
        status_code = 503
        headers = {"Retry-After": "0.05"}
        text = "overloaded"

    def overloaded_post(*a, **kw):
        calls["n"] += 1
        return Overloaded()

    gw._session().post = overloaded_post
    img = np.zeros((1, 32, 32, 3), np.uint8)
    # Ample budget: the 503 earns its one retry (two upstream calls).
    with pytest.raises(UpstreamError) as exc:
        gw._predict_batch(img, deadline=Deadline(5.0))
    assert exc.value.http_status == 503
    assert calls["n"] == 2
    # Nearly expired: no room to sleep out the backoff AND complete a
    # retry -- the 503 surfaces after a single upstream call.
    calls["n"] = 0
    with pytest.raises(UpstreamError) as exc:
        gw._predict_batch(img, deadline=Deadline(0.06))
    assert exc.value.http_status == 503
    assert calls["n"] == 1


# --- graceful drain ---------------------------------------------------------


def test_drain_flips_readyz_sheds_new_work_and_completes_inflight(tmp_path):
    import requests

    spec, server = _make_stub_server("adm-drain", tmp_path, device_ms=400.0)
    base = f"http://127.0.0.1:{server.port}"
    try:
        assert requests.get(f"{base}/readyz", timeout=5).text == "ready"
        inflight_result: list = []

        def inflight():
            inflight_result.append(_post_predict(spec, server, deadline_ms=10_000))

        t = threading.Thread(target=inflight)
        t.start()
        # Wait until the request is admitted (in flight), then drain.
        for _ in range(100):
            if server.admission.inflight > 0:
                break
            threading.Event().wait(0.01)
        assert server.admission.inflight > 0
        server.begin_drain()
        r = requests.get(f"{base}/readyz", timeout=5)
        assert r.status_code == 503 and r.text == "draining"
        r = _post_predict(spec, server, deadline_ms=10_000)
        assert r.status_code == 503
        assert r.json()["shed_reason"] == "draining"
        assert "Retry-After" in r.headers
        # The in-flight request still completes successfully.
        assert server.admission.wait_idle(timeout_s=10.0)
        t.join(timeout=10)
        assert inflight_result and inflight_result[0].status_code == 200
    finally:
        server.shutdown()


# --- deadline propagation gateway -> model tier -> batcher ------------------


def test_deadline_budget_propagates_across_tiers(tmp_path):
    import requests
    from PIL import Image

    spec, server = _make_stub_server("adm-propagate", tmp_path)
    rng = np.random.default_rng(0)
    Image.fromarray(
        rng.integers(0, 256, size=(48, 48, 3), dtype=np.uint8)
    ).save(tmp_path / "img.png")
    img_httpd = HTTPServer(
        ("127.0.0.1", 0),
        partial(SimpleHTTPRequestHandler, directory=str(tmp_path)),
    )
    threading.Thread(target=img_httpd.serve_forever, daemon=True).start()
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name,
        port=0, host="127.0.0.1",
    )
    gw.start()
    try:
        r = requests.post(
            f"http://127.0.0.1:{gw.port}/predict",
            json={"url": f"http://127.0.0.1:{img_httpd.server_address[1]}/img.png"},
            headers={DEADLINE_HEADER: "5000"},
            timeout=30,
        )
        assert r.status_code == 200, r.text
        gw_metrics = requests.get(f"http://127.0.0.1:{gw.port}/metrics", timeout=5).text
        sv_metrics = requests.get(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).text
        # One observation per stage, each strictly later on the same clock,
        # so each tier down the path saw strictly less remaining budget.
        assert _metric(
            gw_metrics, "kdlt_admission_deadline_remaining_ms_count", tier="gateway"
        ) == 1.0
        assert _metric(
            sv_metrics, "kdlt_admission_deadline_remaining_ms_count",
            tier="model-server",
        ) == 1.0
        assert _metric(sv_metrics, "kdlt_admission_batcher_budget_ms_count") == 1.0
        at_gateway = _metric(
            gw_metrics, "kdlt_admission_deadline_remaining_ms_sum", tier="gateway"
        )
        at_server = _metric(
            sv_metrics, "kdlt_admission_deadline_remaining_ms_sum",
            tier="model-server",
        )
        at_batcher = _metric(sv_metrics, "kdlt_admission_batcher_budget_ms_sum")
        assert 0.0 < at_gateway <= 5000.0
        assert 0.0 < at_batcher < at_server < at_gateway, (
            at_gateway, at_server, at_batcher,
        )
    finally:
        gw.shutdown()
        server.shutdown()
        img_httpd.shutdown()


# --- derived Retry-After: live queue/hold state, clamped, jittered ----------


def test_retry_after_derived_from_queue_and_hold_ewma():
    from kubernetes_deep_learning_tpu.serving.admission.limiter import (
        RETRY_AFTER_JITTER,
        RETRY_AFTER_MAX_S,
        RETRY_AFTER_MIN_S,
        AdaptiveLimiter,
    )

    lim = AdaptiveLimiter(
        min_limit=1, max_limit=4, initial=4, target_wait_s=0.0, budgets=None
    )
    lo = 1.0 - RETRY_AFTER_JITTER
    hi = 1.0 + RETRY_AFTER_JITTER
    # Cold EWMA: the 0.1 s fallback over 4 slots lands under the floor --
    # the hint clamps to RETRY_AFTER_MIN_S BEFORE jitter is applied.
    samples = [lim.retry_after_s() for _ in range(64)]
    assert all(
        RETRY_AFTER_MIN_S * lo <= s <= RETRY_AFTER_MIN_S * hi for s in samples
    ), (min(samples), max(samples))
    assert max(samples) > min(samples)  # jitter actually varies the hint
    # Observed 2 s holds: (waiters+1)/limit * hold = 1/4 * 2 = 0.5 s base.
    lim.release(held_s=2.0)
    samples = [lim.retry_after_s() for _ in range(64)]
    assert all(0.5 * lo <= s <= 0.5 * hi for s in samples), (
        min(samples), max(samples),
    )
    # A confused EWMA (or a very deep queue) must not park clients: the
    # base clamps at RETRY_AFTER_MAX_S, so the jittered hint never
    # exceeds max * (1 + jitter).
    lim._hold_ewma_s = 1_000.0
    samples = [lim.retry_after_s() for _ in range(64)]
    assert all(
        RETRY_AFTER_MAX_S * lo <= s <= RETRY_AFTER_MAX_S * hi for s in samples
    ), (min(samples), max(samples))


def test_client_caps_honored_retry_after(monkeypatch):
    # A server hinting Retry-After: 60 must not park the client for a
    # minute: predict_url caps the honored value at RETRY_AFTER_CAP_S
    # (plus its own decorrelation jitter) before sleeping.
    import requests

    from kubernetes_deep_learning_tpu.serving import client as client_mod

    class Shed503:
        status_code = 503
        headers = {"Retry-After": "60"}

        def raise_for_status(self):
            raise requests.HTTPError("503", response=self)

    slept: list[float] = []
    monkeypatch.setattr(requests, "post", lambda *a, **kw: Shed503())
    monkeypatch.setattr(client_mod.time, "sleep", slept.append)
    stats: dict = {}
    with pytest.raises(requests.HTTPError):
        client_mod.predict_url(
            "http://gw", "http://img", timeout=100.0, retries=1, stats=stats
        )
    assert stats["retried_shed"] == 1
    assert len(slept) == 1
    cap = client_mod.RETRY_AFTER_CAP_S
    assert cap <= slept[0] <= cap * 1.25 + 0.01, slept


# --- per-model budgets + priority classes in the limiter --------------------


def _wait_for(predicate, timeout_s=2.0):
    deadline = threading.Event()
    for _ in range(int(timeout_s / 0.005)):
        if predicate():
            return True
        deadline.wait(0.005)
    return predicate()


def test_budget_shares_follow_weights():
    from kubernetes_deep_learning_tpu.serving.admission.limiter import (
        AdaptiveLimiter,
    )

    lim = AdaptiveLimiter(
        min_limit=1, max_limit=8, initial=8, budgets={"a": 1.0, "b": 3.0}
    )
    lim.acquire(model="a")
    lim.acquire(model="b")
    # Weighted slices of the live limit over the ACTIVE model set.
    assert lim.shares() == {"a": 2.0, "b": 6.0}
    lim.release(model="b")
    # b idle again: the sole active model owns the whole limit (work-
    # conserving -- budgets bite only under contention).
    assert lim.shares() == {"a": 8.0}


def test_under_share_arrival_evicts_over_share_waiter():
    from kubernetes_deep_learning_tpu.serving.admission.limiter import (
        AdaptiveLimiter,
    )
    from kubernetes_deep_learning_tpu.serving.admission.shed import Shed

    lim = AdaptiveLimiter(
        min_limit=1, max_limit=2, initial=2, queue_cap=1,
        budgets={"a": 1.0, "b": 1.0},
    )
    # Tenant a takes BOTH slots (one borrowed from b's idle share) and
    # queues a third request -- over-share, at the waiter cap.
    lim.acquire(model="a")
    lim.acquire(model="a")
    outcome: dict = {}

    def over_share_waiter():
        try:
            lim.acquire(budget_s=40.0, model="a")
            outcome["a"] = "granted"
        except Shed as e:
            outcome["a"] = e

    ta = threading.Thread(target=over_share_waiter)
    ta.start()
    assert _wait_for(lambda: lim.queue_depth == 1)
    # b arrives at the cap: the over-share a waiter is strictly worse and
    # is evicted (reason budget_exhausted -- the borrowed capacity is
    # handed back first), with a live-derived Retry-After.
    granted: list[float] = []
    tb = threading.Thread(
        target=lambda: granted.append(lim.acquire(budget_s=40.0, model="b"))
    )
    tb.start()
    ta.join(timeout=5)
    shed = outcome["a"]
    assert isinstance(shed, Shed), shed
    assert shed.reason == "budget_exhausted"
    assert 0.0 < shed.retry_after_s <= 12.5
    # The next freed slot goes to the under-share owner.
    lim.release(model="a")
    tb.join(timeout=5)
    assert granted, "b's request was never granted"
    assert lim.inflight == 2


def test_higher_class_arrival_preempts_lower_class_waiter():
    from kubernetes_deep_learning_tpu.serving.admission.limiter import (
        AdaptiveLimiter,
    )
    from kubernetes_deep_learning_tpu.serving.admission.shed import Shed

    lim = AdaptiveLimiter(
        min_limit=1, max_limit=2, initial=2, queue_cap=1, budgets=None
    )
    lim.acquire()
    lim.acquire()
    outcome: dict = {}

    def lowly_waiter():
        try:
            lim.acquire(budget_s=40.0, priority="best-effort")
            outcome["be"] = "granted"
        except Shed as e:
            outcome["be"] = e

    t = threading.Thread(target=lowly_waiter)
    t.start()
    assert _wait_for(lambda: lim.queue_depth == 1)
    granted: list[float] = []
    ti = threading.Thread(
        target=lambda: granted.append(
            lim.acquire(budget_s=40.0, priority="interactive")
        )
    )
    ti.start()
    t.join(timeout=5)
    shed = outcome["be"]
    assert isinstance(shed, Shed), shed
    assert shed.reason == "preempted"
    lim.release()
    ti.join(timeout=5)
    assert granted, "the interactive request was never granted"


def test_newcomer_no_better_than_queue_sheds_queue_full():
    from kubernetes_deep_learning_tpu.serving.admission.limiter import (
        AdaptiveLimiter,
    )
    from kubernetes_deep_learning_tpu.serving.admission.shed import Shed

    lim = AdaptiveLimiter(
        min_limit=1, max_limit=2, initial=2, queue_cap=1, budgets=None
    )
    lim.acquire()
    lim.acquire()
    parked: dict = {}

    def interactive_waiter():
        try:
            lim.acquire(budget_s=40.0, priority="interactive")
            parked["i"] = "granted"
        except Shed as e:
            parked["i"] = e

    t = threading.Thread(target=interactive_waiter)
    t.start()
    assert _wait_for(lambda: lim.queue_depth == 1)
    # A best-effort arrival finds nobody strictly worse to evict: IT is
    # the one shed, and the queued interactive request keeps its place.
    with pytest.raises(Shed) as exc:
        lim.acquire(budget_s=40.0, priority="best-effort")
    assert exc.value.reason == "queue_full"
    assert lim.queue_depth == 1
    lim.release()
    t.join(timeout=5)
    assert parked["i"] == "granted"
