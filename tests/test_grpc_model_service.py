"""TF-Serving ModelService surface (GetModelStatus + HandleReloadConfig)
over the real gRPC server -- the management RPCs the reference's tier
carries in the TF-Serving binary (reference tf-serving.dockerfile:2)."""

from __future__ import annotations

import grpc
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.export.exporter import export_model
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.serving.grpc_model_service import (
    MODEL_SERVICE_NAME,
)
from kubernetes_deep_learning_tpu.serving.grpc_predict import serve_grpc
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
from kubernetes_deep_learning_tpu.serving.tfs_gen.tensorflow_serving.apis import (
    get_model_status_pb2,
    model_management_pb2,
)


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    spec = register_spec(
        ModelSpec(
            name="msvc-vit",
            family="vit-tiny",
            input_shape=(16, 16, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
        )
    )
    root = tmp_path_factory.mktemp("msvc-models")
    export_model(spec, init_variables(spec, seed=0), str(root))
    server = ModelServer(str(root), port=0, buckets=(1, 2), max_delay_ms=1.0)
    server.warmup()
    grpc_server, port = serve_grpc(server, 0, host="127.0.0.1")
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    status_call = channel.unary_unary(
        f"/{MODEL_SERVICE_NAME}/GetModelStatus",
        request_serializer=get_model_status_pb2.GetModelStatusRequest.SerializeToString,
        response_deserializer=get_model_status_pb2.GetModelStatusResponse.FromString,
    )
    reload_call = channel.unary_unary(
        f"/{MODEL_SERVICE_NAME}/HandleReloadConfigRequest",
        request_serializer=model_management_pb2.ReloadConfigRequest.SerializeToString,
        response_deserializer=model_management_pb2.ReloadConfigResponse.FromString,
    )
    yield spec, str(root), server, status_call, reload_call, port
    channel.close()
    grpc_server.stop(grace=None)
    server.shutdown()


def test_get_model_status_available(stack):
    spec, _root, _server, status_call, _, _ = stack
    req = get_model_status_pb2.GetModelStatusRequest()
    req.model_spec.name = spec.name
    resp = status_call(req, timeout=30)
    (st,) = resp.model_version_status
    assert st.version == 1
    assert st.state == get_model_status_pb2.ModelVersionStatus.AVAILABLE
    assert st.status.error_code == 0

    # Version pinning mirrors Predict/GetModelMetadata's contract.
    req.model_spec.version.value = 1
    assert status_call(req, timeout=30).model_version_status[0].version == 1
    req.model_spec.version.value = 9
    with pytest.raises(grpc.RpcError) as ei:
        status_call(req, timeout=30)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND

    req2 = get_model_status_pb2.GetModelStatusRequest()
    req2.model_spec.name = "nope"
    with pytest.raises(grpc.RpcError) as ei:
        status_call(req2, timeout=30)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_reload_config_picks_up_new_version(stack):
    spec, root, server, status_call, reload_call, _ = stack
    # Drop a v2 artifact, then apply a config naming the model: the reload
    # must synchronously hot-load v2 (the version watcher's scan).
    export_model(spec, init_variables(spec, seed=5), root)
    assert art.latest_version(root, spec.name) == 2

    req = model_management_pb2.ReloadConfigRequest()
    mc = req.config.model_config_list.config.add()
    mc.name = spec.name
    resp = reload_call(req, timeout=60)
    assert resp.status.error_code == 0, resp.status.error_message
    assert server.models[spec.name].version == 2

    sreq = get_model_status_pb2.GetModelStatusRequest()
    sreq.model_spec.name = spec.name
    assert status_call(sreq, timeout=30).model_version_status[0].version == 2


def test_reload_config_rejections(stack):
    spec, _root, _server, _status, reload_call, _ = stack
    # Empty list = TF-Serving's unload-everything: refused loudly.
    with pytest.raises(grpc.RpcError) as ei:
        reload_call(model_management_pb2.ReloadConfigRequest(), timeout=30)
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    req = model_management_pb2.ReloadConfigRequest()
    req.config.model_config_list.SetInParent()
    with pytest.raises(grpc.RpcError) as ei:
        reload_call(req, timeout=30)
    assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION

    # base_path outside the server's root: refused, not half-honored.
    req = model_management_pb2.ReloadConfigRequest()
    mc = req.config.model_config_list.config.add()
    mc.name = spec.name
    mc.base_path = "/somewhere/else"
    with pytest.raises(grpc.RpcError) as ei:
        reload_call(req, timeout=30)
    assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION

    # Unknown model name: reload applies, but the response status says
    # NOT_FOUND (TF-Serving's StatusProto convention, not a transport error).
    req = model_management_pb2.ReloadConfigRequest()
    req.config.model_config_list.config.add().name = "ghost"
    resp = reload_call(req, timeout=60)
    assert resp.status.error_code == 5
    assert "ghost" in resp.status.error_message


def test_reload_config_rejects_unknown_model_config_fields(stack):
    """A stock client setting a ModelConfig field outside the hand-written
    subset (e.g. model_version_policy, field 7) must be refused, not
    silently ignored while the reload reports OK."""
    spec, _root, _server, _status, _reload, grpc_port = stack
    # Splice a field-7 submessage into the nested wire encoding by hand
    # (tag 0x3A = field 7, wire type 2).
    inner = model_management_pb2.ModelConfig(
        name=spec.name
    ).SerializeToString() + bytes([0x3A, 0x02, 0x08, 0x01])
    lst = bytes([0x0A, len(inner)]) + inner     # ModelConfigList.config
    cfg = bytes([0x0A, len(lst)]) + lst         # ModelServerConfig.model_config_list
    reqb = bytes([0x0A, len(cfg)]) + cfg        # ReloadConfigRequest.config
    parsed = model_management_pb2.ReloadConfigRequest.FromString(reqb)
    assert parsed.config.model_config_list.config[0].name == spec.name

    channel = grpc.insecure_channel(f"127.0.0.1:{grpc_port}")
    raw_call = channel.unary_unary(
        f"/{MODEL_SERVICE_NAME}/HandleReloadConfigRequest",
        request_serializer=lambda b: b,
        response_deserializer=model_management_pb2.ReloadConfigResponse.FromString,
    )
    with pytest.raises(grpc.RpcError) as ei:
        raw_call(reqb, timeout=30)
    assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    assert "unsupported" in ei.value.details()
    channel.close()
