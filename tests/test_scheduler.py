"""Unified SLO-aware scheduling core (runtime/scheduler.py): per-model
lanes, cross-model arbitration (fifo vs weighted earliest-effective-
deadline with weight floors), the shared multi-engine dispatcher, engine
hot-swap semantics, and the invariant metrics contract.  All device-free
(StubEngine simulated devices)."""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.runtime import BatcherClosed, QueueFull
from kubernetes_deep_learning_tpu.runtime.scheduler import (
    Lane,
    UnifiedScheduler,
    resolve_policy,
    resolve_weights,
)
from kubernetes_deep_learning_tpu.runtime.stub import StubEngine, stub_logits
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

SHAPE = (8, 8, 3)


def _spec(name: str, n_labels: int = 3) -> ModelSpec:
    return register_spec(ModelSpec(
        name=name, family="xception", input_shape=SHAPE,
        labels=tuple("abcdefg"[:n_labels]),
    ))


def _engine(name: str, device_ms=0.0, buckets=(1, 2, 4), n_labels=3):
    return StubEngine(
        SimpleNamespace(spec=_spec(name, n_labels)), buckets=buckets,
        async_device=True, device_ms_per_batch=device_ms,
    )


def _imgs(n: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, *SHAPE), dtype=np.uint8)


# --- knob resolution -------------------------------------------------------


def test_resolve_policy_and_weights(monkeypatch):
    assert resolve_policy("fifo") == "fifo"
    assert resolve_policy("WEIGHTED_DEADLINE") == "weighted_deadline"
    assert resolve_policy("garbage") == "weighted_deadline"  # degrade, not die
    monkeypatch.setenv("KDLT_SCHED_POLICY", "fifo")
    assert resolve_policy() == "fifo"
    assert resolve_weights("a=2, b=0.5,junk,c=oops,=1,d=-3") == {
        "a": 2.0, "b": 0.5, "d": 1e-3,  # non-positive clamped, junk skipped
    }
    monkeypatch.setenv("KDLT_SCHED_WEIGHTS", "m=4")
    assert resolve_weights() == {"m": 4.0}


# --- correctness: routing, fan-out, chunks ---------------------------------


def test_two_models_share_one_dispatcher_with_correct_fanout():
    ea, eb = _engine("sched-a", 2.0), _engine("sched-b", 2.0, n_labels=2)
    reg = metrics_lib.Registry()
    s = UnifiedScheduler(registry=reg)
    s.register("sched-a", ea)
    s.register("sched-b", eb)
    try:
        imgs = _imgs(8)
        futs_a = [s.submit("sched-a", imgs[i]) for i in range(4)]
        futs_b = [s.submit("sched-b", imgs[i + 4]) for i in range(4)]
        rows_a = [f.result(timeout=10) for f in futs_a]
        rows_b = [f.result(timeout=10) for f in futs_b]
        want_a, want_b = stub_logits(imgs[:4], 3), stub_logits(imgs[4:], 2)
        for i in range(4):  # per-request rows, never another model's
            assert np.array_equal(rows_a[i], want_a[i])
            assert np.array_equal(rows_b[i], want_b[i])
        # A pre-formed chunk stays contiguous and ordered.
        chunk = s.submit_batch("sched-b", imgs[:3]).result(timeout=10)
        assert np.array_equal(chunk, stub_logits(imgs[:3], 2))
        page = reg.render()
        # The invariant metric contract: batcher series under the model
        # label, pipeline stages attributed per model, scheduler gauges.
        assert 'kdlt_batcher_batch_size_count{model="sched-a"}' in page
        assert 'kdlt_pipeline_execute_seconds_count{model="sched-b"}' in page
        assert "kdlt_sched_models 2.0" in page
        assert 'kdlt_sched_policy{policy="weighted_deadline"} 1.0' in page
    finally:
        s.close()
        ea.close()
        eb.close()


def test_submit_validates_model_shape_dtype_and_chunk_size():
    e = _engine("sched-val")
    s = UnifiedScheduler(registry=metrics_lib.Registry())
    s.register("sched-val", e)
    try:
        with pytest.raises(ValueError, match="no scheduling lane"):
            s.submit("nope", _imgs(1)[0])
        with pytest.raises(ValueError, match="uint8"):
            s.submit("sched-val", _imgs(1)[0].astype(np.float32))
        with pytest.raises(ValueError, match="shape"):
            s.submit("sched-val", np.zeros((4, 4, 3), np.uint8))
        with pytest.raises(ValueError, match="max bucket"):
            s.submit_batch("sched-val", _imgs(5))  # max bucket is 4
    finally:
        s.close()
        e.close()


def test_queue_cap_sheds_with_queue_full():
    e = _engine("sched-cap", device_ms=50.0)
    s = UnifiedScheduler(registry=metrics_lib.Registry(), queue_cap=4)
    s.register("sched-cap", e)
    try:
        futs = [s.submit("sched-cap", _imgs(1)[0]) for _ in range(4)]
        with pytest.raises(QueueFull):
            for _ in range(8):  # the dispatch thread may drain a few
                s.submit("sched-cap", _imgs(1)[0])
        for f in futs:
            f.result(timeout=10)
    finally:
        s.close()
        e.close()


# --- lifecycle: hot-swap, unregister, close --------------------------------


def test_engine_hot_swap_preserves_lane_and_stale_close_is_noop():
    e1 = _engine("sched-swap", 1.0)
    s = UnifiedScheduler(registry=metrics_lib.Registry())
    lane = s.register("sched-swap", e1)
    try:
        assert s.submit("sched-swap", _imgs(1)[0]).result(timeout=10) is not None
        e2 = _engine("sched-swap", 1.0)
        assert s.register("sched-swap", e2) is lane  # same lane, new engine
        assert lane.engine is e2
        # The superseded owner's unregister must NOT tear down the lane.
        s.unregister("sched-swap", engine=e1)
        assert s.lane("sched-swap") is lane
        assert s.submit("sched-swap", _imgs(1)[0]).result(timeout=10) is not None
        # The current owner's unregister does, failing queued work loudly.
        s.unregister("sched-swap", engine=e2)
        assert s.lane("sched-swap") is None
        with pytest.raises(ValueError, match="no scheduling lane"):
            s.submit("sched-swap", _imgs(1)[0])
        e2.close()
    finally:
        s.close()
        e1.close()


def test_close_without_drain_fails_queued_waiters():
    e = _engine("sched-close", device_ms=200.0)
    s = UnifiedScheduler(registry=metrics_lib.Registry())
    s.register("sched-close", e)
    futs = [s.submit("sched-close", _imgs(1)[0]) for _ in range(6)]
    s.close(drain=False)
    e.close()
    outcomes = []
    for f in futs:
        try:
            f.result(timeout=10)
            outcomes.append("ok")
        except BatcherClosed:
            outcomes.append("closed")
        except Exception:
            outcomes.append("other")
    # Every waiter resolves (no strands); queued-but-undispatched ones get
    # the typed BatcherClosed the server maps to a retryable 5xx.
    assert "other" not in outcomes
    with pytest.raises(BatcherClosed):
        s.submit("sched-close", _imgs(1)[0])


# --- arbitration policy ----------------------------------------------------


def _lane(name, weight=1.0, cost_s=0.0, deadlines=(), enq_ts=(), served=0.0):
    lane = Lane(
        name, engine=SimpleNamespace(max_batch=4), weight=weight,
        max_delay_s=0.002, queue_cap=2048,
        metrics=metrics_lib.scheduler_lane_metrics(
            metrics_lib.Registry(), name
        ),
    )
    lane.cost_per_image_s = cost_s or None
    now = time.monotonic()
    for i, d in enumerate(deadlines):
        u = SimpleNamespace(
            n=1, deadline_abs=None if d is None else now + d,
            enq_t=now + (enq_ts[i] if i < len(enq_ts) else 0.0),
        )
        lane.queue.append(u)
        lane.pending_images += 1
    lane.served_s = served
    return lane


def test_fifo_policy_picks_the_oldest_head():
    s = UnifiedScheduler(registry=metrics_lib.Registry(), policy="fifo")
    try:
        old = _lane("old", deadlines=[5.0], enq_ts=[-3.0])
        young = _lane("young", deadlines=[0.01], enq_ts=[0.0])
        # FIFO ignores urgency entirely: the older head wins even though
        # the young lane's deadline is about to pass.
        assert s._choose([old, young], time.monotonic()) is old
    finally:
        s.close()


def test_weighted_policy_orders_by_effective_deadline():
    s = UnifiedScheduler(registry=metrics_lib.Registry())
    try:
        now = time.monotonic()
        loose = _lane("loose", deadlines=[5.0], enq_ts=[-3.0])
        tight = _lane("tight", deadlines=[0.2], enq_ts=[0.0])
        assert s._choose([loose, tight], now) is tight
        # The cost estimate shifts urgency: same wire deadline, but the
        # expensive model must START earlier (latest viable start wins).
        slow = _lane("slow", cost_s=0.3, deadlines=[1.0])
        fast = _lane("fast", cost_s=0.001, deadlines=[1.0])
        assert s._choose([slow, fast], now) is slow
    finally:
        s.close()


def test_weight_floor_rescues_a_starved_lane():
    s = UnifiedScheduler(registry=metrics_lib.Registry())
    try:
        now = time.monotonic()
        # The hog consumed ~all recent device time AND holds the earlier
        # deadline (the EDF-under-overload domino); the starved lane is
        # below its 50% fair-share floor, so the floor preempts EDF.
        hog = _lane("hog", served=10.0, deadlines=[0.05])
        starved = _lane("starved", served=0.0, deadlines=[1.0])
        assert s._choose([hog, starved], now) is starved
        assert starved.m["floor_boosts"].value == 1.0
        # With shares in balance the floor stands down and EDF decides.
        hog2 = _lane("hog2", served=1.0, deadlines=[0.05])
        fed = _lane("fed", served=1.0, deadlines=[1.0])
        assert s._choose([hog2, fed], now) is hog2
    finally:
        s.close()


def test_fifo_starves_tight_deadlines_where_weighted_serves_them():
    """The multimodel-ab scenario in miniature: a heavy overloaded lane +
    a light tight-deadline lane.  Weighted serves the light lane inside
    its deadline; FIFO leaves it behind the heavy backlog."""

    def run(policy: str) -> float:
        heavy = _engine(f"mm-{policy}-heavy", device_ms=60.0)
        light = _engine(f"mm-{policy}-light", device_ms=1.0, n_labels=2)
        s = UnifiedScheduler(registry=metrics_lib.Registry(), policy=policy)
        s.register(f"mm-{policy}-heavy", heavy)
        s.register(f"mm-{policy}-light", light)
        from kubernetes_deep_learning_tpu.serving.admission import Deadline

        try:
            # Saturate the heavy lane (each batch 60 ms, bucket 4).
            heavy_futs = [
                s.submit(f"mm-{policy}-heavy", _imgs(1)[0],
                         deadline=Deadline(10.0))
                for _ in range(40)
            ]
            time.sleep(0.15)  # let the heavy backlog establish itself
            t0 = time.monotonic()
            light_fut = s.submit(
                f"mm-{policy}-light", _imgs(1)[0], deadline=Deadline(0.25)
            )
            light_fut.result(timeout=10)
            light_latency = time.monotonic() - t0
            for f in heavy_futs:
                f.result(timeout=30)
            return light_latency
        finally:
            s.close()
            heavy.close()
            light.close()

    weighted = run("weighted_deadline")
    fifo = run("fifo")
    # Weighted: the light request preempts the backlog (sub-deadline).
    # FIFO: it waits out most of the remaining heavy queue head-of-line.
    assert weighted < 0.25, f"weighted served the light lane in {weighted:.3f}s"
    assert fifo > 2 * weighted, (weighted, fifo)


# --- request traces --------------------------------------------------------


def test_scheduler_records_queue_wait_and_pipeline_spans():
    from kubernetes_deep_learning_tpu.utils import trace as trace_lib

    e = _engine("sched-trace", 1.0)
    s = UnifiedScheduler(registry=metrics_lib.Registry())
    s.register("sched-trace", e)
    tracer = trace_lib.Tracer("test")
    try:
        rt = tracer.request_trace("rid-sched")
        s.submit("sched-trace", _imgs(1)[0], trace=rt).result(timeout=10)
        names = {sp["name"] for sp in tracer.spans("rid-sched")}
        # The same span contract the batchers uphold: queue wait + the
        # four pipeline stages.
        assert "batcher.queue_wait" in names
        for stage in ("enqueue_wait", "dispatch", "execute", "readback"):
            assert f"pipeline.{stage}" in names
    finally:
        s.close()
        e.close()
