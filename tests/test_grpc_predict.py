"""gRPC PredictionService wire-compatibility tests.

The client side of each test marshals a PredictRequest exactly the way the
reference gateway does (reference model_server.py:35-55): model_spec.name +
signature_name='serving_default', the input under the SavedModel signature's
tensor name, data as tf.make_tensor_proto would emit it (raw little-endian
tensor_content for a non-empty float32 array), a 20 s deadline, and the
response read back through ``outputs[...].float_val``.  No TensorFlow is in
the loop -- the protos are the hand-written wire-compatible subset in
serving/tfs_protos.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import grpc

from kubernetes_deep_learning_tpu.export import export_model
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.serving.grpc_predict import (
    SERVICE_NAME,
    array_from_tensor_proto,
    serve_grpc,
    tensor_proto_from_array,
)
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
from kubernetes_deep_learning_tpu.serving.tfs_gen.tensorflow_serving.apis import (
    predict_pb2,
)


@pytest.fixture(scope="module")
def grpc_stack(tmp_path_factory):
    spec = register_spec(
        ModelSpec(
            name="grpc-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("dress", "hat", "pants", "shirt"),
            preprocessing="tf",
            # The reference SavedModel's signature tensor names
            # (reference guide.md:220-231).
            compat_input_name="input_8",
            compat_output_name="dense_7",
        )
    )
    root = tmp_path_factory.mktemp("models")
    variables = init_variables(spec, seed=11)
    export_model(spec, variables, str(root), dtype=np.float32)

    server = ModelServer(str(root), port=0, buckets=(1, 2, 4), max_delay_ms=1.0)
    server.warmup()
    grpc_server, port = serve_grpc(server, 0, host="127.0.0.1")

    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    # The reference builds its stub from protoc-generated service code
    # (PredictionServiceStub, reference model_server.py:16); the multicallable
    # below is the identical wire operation -- same method path, same
    # serialized bytes.
    predict = channel.unary_unary(
        f"/{SERVICE_NAME}/Predict",
        request_serializer=predict_pb2.PredictRequest.SerializeToString,
        response_deserializer=predict_pb2.PredictResponse.FromString,
    )
    yield spec, server, predict, channel

    channel.close()
    grpc_server.stop(grace=None)
    server.shutdown()


def _reference_style_request(spec, X: np.ndarray) -> predict_pb2.PredictRequest:
    """Marshal as reference model_server.py:39-43 does (make_request)."""
    req = predict_pb2.PredictRequest()
    req.model_spec.name = spec.name
    req.model_spec.signature_name = "serving_default"
    # tf.make_tensor_proto(X, shape=X.shape) on float32 emits tensor_content.
    req.inputs["input_8"].CopyFrom(tensor_proto_from_array(X, use_content=True))
    return req


def test_reference_client_marshalling_roundtrip(grpc_stack):
    spec, server, predict, _ = grpc_stack
    rng = np.random.default_rng(0)
    # The reference gateway sends preprocessed float32 ("tf" mode: [-1, 1]).
    X = rng.uniform(-1.0, 1.0, size=(1, *spec.input_shape)).astype(np.float32)

    result = predict(_reference_style_request(spec, X), timeout=20.0)

    # Reference response handling (model_server.py:46-49): float_val under
    # the SavedModel output tensor name.
    pred = result.outputs["dense_7"].float_val
    assert len(pred) == spec.num_classes
    expected = server.models[spec.name].engine.predict(X)
    np.testing.assert_allclose(
        np.array(pred).reshape(1, -1), expected, rtol=1e-5, atol=1e-5
    )
    # The spec-native output key carries the same tensor.
    np.testing.assert_array_equal(
        result.outputs["dense_7"].float_val, result.outputs[spec.output_name].float_val
    )
    assert result.model_spec.version.value >= 1


def test_uint8_content_and_shapes(grpc_stack):
    """uint8 wire path (this framework's preferred dtype) over gRPC."""
    spec, server, predict, _ = grpc_stack
    rng = np.random.default_rng(1)
    images = rng.integers(0, 256, size=(3, *spec.input_shape), dtype=np.uint8)
    req = predict_pb2.PredictRequest()
    req.model_spec.name = spec.name
    req.inputs[spec.input_name].CopyFrom(
        tensor_proto_from_array(images, use_content=True)
    )
    result = predict(req, timeout=20.0)
    got = np.array(result.outputs[spec.output_name].float_val).reshape(3, -1)
    np.testing.assert_allclose(
        got, server.models[spec.name].engine.predict(images), rtol=1e-5, atol=1e-5
    )


def test_float_val_and_broadcast_marshalling(grpc_stack):
    """Packed float_val requests and the single-element broadcast convention."""
    spec, server, predict, _ = grpc_stack
    rng = np.random.default_rng(2)
    X = rng.uniform(-1, 1, size=(1, *spec.input_shape)).astype(np.float32)
    req = _reference_style_request(spec, X)
    req.inputs["input_8"].CopyFrom(tensor_proto_from_array(X))  # float_val form
    a = np.array(predict(req, timeout=20.0).outputs[spec.output_name].float_val)

    expected = server.models[spec.name].engine.predict(X)
    np.testing.assert_allclose(a.reshape(1, -1), expected, rtol=1e-5, atol=1e-5)

    # Broadcast: one value + full shape (tf.make_tensor_proto scalar form).
    tp = req.inputs["input_8"]
    del tp.float_val[:]
    tp.ClearField("tensor_content")
    tp.float_val.append(0.25)
    b = np.array(predict(req, timeout=20.0).outputs[spec.output_name].float_val)
    const = np.full((1, *spec.input_shape), 0.25, np.float32)
    np.testing.assert_allclose(
        b.reshape(1, -1),
        server.models[spec.name].engine.predict(const),
        rtol=1e-5,
        atol=1e-5,
    )


def test_int32_pixels_normalize_like_uint8(grpc_stack):
    """Integer tensors are pixels: they must take the normalize-on-device
    path, not be misread as pre-normalized floats (tf.make_tensor_proto
    emits DT_INT32 for plain Python int lists)."""
    spec, server, predict, _ = grpc_stack
    rng = np.random.default_rng(5)
    pixels = rng.integers(0, 256, size=(1, *spec.input_shape), dtype=np.int32)
    req = predict_pb2.PredictRequest()
    req.model_spec.name = spec.name
    req.inputs[spec.input_name].CopyFrom(tensor_proto_from_array(pixels))
    got = np.array(predict(req, timeout=20.0).outputs[spec.output_name].float_val)
    expected = server.models[spec.name].engine.predict(pixels.astype(np.uint8))
    np.testing.assert_allclose(got.reshape(1, -1), expected, rtol=1e-5, atol=1e-5)

    req.inputs[spec.input_name].CopyFrom(
        tensor_proto_from_array(pixels + 300)  # out of pixel range
    )
    with pytest.raises(grpc.RpcError) as e:
        predict(req, timeout=20.0)
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_grpc_error_statuses(grpc_stack):
    spec, _, predict, _ = grpc_stack
    rng = np.random.default_rng(3)
    X = rng.uniform(-1, 1, size=(1, *spec.input_shape)).astype(np.float32)

    req = _reference_style_request(spec, X)
    req.model_spec.name = "no-such-model"
    with pytest.raises(grpc.RpcError) as e:
        predict(req, timeout=20.0)
    assert e.value.code() == grpc.StatusCode.NOT_FOUND

    req = _reference_style_request(spec, X)
    req.model_spec.signature_name = "wrong_signature"
    with pytest.raises(grpc.RpcError) as e:
        predict(req, timeout=20.0)
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    bad = rng.uniform(-1, 1, size=(1, 32, 32, 3)).astype(np.float32)
    with pytest.raises(grpc.RpcError) as e:
        predict(_reference_style_request(spec, bad), timeout=20.0)
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_tensor_proto_numpy_roundtrip():
    rng = np.random.default_rng(4)
    for arr in (
        rng.normal(size=(2, 3, 4)).astype(np.float32),
        rng.integers(0, 256, size=(5, 7), dtype=np.uint8),
        rng.normal(size=(3,)).astype(np.float64),
        rng.integers(-100, 100, size=(2, 2), dtype=np.int64),
        rng.normal(size=(4, 2)).astype(np.float16),
    ):
        for use_content in (False, True):
            tp = tensor_proto_from_array(arr, use_content=use_content)
            back = array_from_tensor_proto(tp)
            assert back.dtype == arr.dtype and back.shape == arr.shape
            np.testing.assert_array_equal(back, arr)


def test_modelspec_compat_fields_roundtrip():
    spec = ModelSpec(
        name="s",
        family="xception",
        input_shape=(96, 96, 3),
        labels=("a", "b"),
        compat_input_name="input_8",
        compat_output_name="dense_7",
    )
    again = ModelSpec.from_json(spec.to_json())
    assert again == spec
    # Old artifacts (round-1 spec.json without the compat fields) still load.
    legacy = dataclasses.asdict(spec)
    legacy.pop("compat_input_name")
    legacy.pop("compat_output_name")
    import json as _json

    old = ModelSpec.from_json(_json.dumps(legacy))
    assert old.compat_input_name == "" and old.compat_output_name == ""


def test_get_model_metadata_signature(grpc_stack):
    """TF-Serving's GetModelMetadata (round-2 gap: UNIMPLEMENTED): the
    response must carry the ModelSpec-derived serving_default signature in
    the binary's exact shape -- SignatureDefMap packed in an Any under
    metadata["signature_def"], compat tensor names, -1 batch dims."""
    from kubernetes_deep_learning_tpu.serving.grpc_predict import SERVICE_NAME
    from kubernetes_deep_learning_tpu.serving.tfs_gen.tensorflow_serving.apis import (
        get_model_metadata_pb2,
    )

    spec, server, _, channel = grpc_stack
    call = channel.unary_unary(
        f"/{SERVICE_NAME}/GetModelMetadata",
        request_serializer=get_model_metadata_pb2.GetModelMetadataRequest.SerializeToString,
        response_deserializer=get_model_metadata_pb2.GetModelMetadataResponse.FromString,
    )
    req = get_model_metadata_pb2.GetModelMetadataRequest()
    req.model_spec.name = spec.name
    req.metadata_field.append("signature_def")
    resp = call(req, timeout=30)
    assert resp.model_spec.name == spec.name
    assert resp.model_spec.version.value == 1
    packed = resp.metadata["signature_def"]
    assert packed.type_url.endswith("tensorflow.serving.SignatureDefMap")
    sdmap = get_model_metadata_pb2.SignatureDefMap()
    assert packed.Unpack(sdmap)
    sig = sdmap.signature_def["serving_default"]
    assert sig.method_name == "tensorflow/serving/predict"
    info = sig.inputs["input_8"]  # the reference's compat tensor name
    assert info.name == "input_8:0" and info.dtype == 1
    assert [d.size for d in info.tensor_shape.dim] == [-1, 96, 96, 3]
    out = sig.outputs["dense_7"]
    assert [d.size for d in out.tensor_shape.dim] == [-1, 4]

    # unknown model -> NOT_FOUND, TF-Serving's wording
    req2 = get_model_metadata_pb2.GetModelMetadataRequest()
    req2.model_spec.name = "nope"
    with pytest.raises(grpc.RpcError) as ei:
        call(req2, timeout=30)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND

    # Pinning the loaded version resolves; pinning any other is NOT_FOUND
    # with TF-Serving's Specific() wording (ADVICE r3: metadata must never
    # be silently attributed to a different version than requested).
    req3 = get_model_metadata_pb2.GetModelMetadataRequest()
    req3.model_spec.name = spec.name
    req3.model_spec.version.value = 1
    assert call(req3, timeout=30).model_spec.version.value == 1
    req3.model_spec.version.value = 7
    with pytest.raises(grpc.RpcError) as ei:
        call(req3, timeout=30)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    assert f"Specific({spec.name}, 7)" in ei.value.details()


def test_predict_version_pinning(grpc_stack):
    """Predict with model_spec.version: the loaded version serves; any
    other version is NOT_FOUND (same contract as GetModelMetadata)."""
    spec, _, predict, _ = grpc_stack
    rng = np.random.default_rng(5)
    X = rng.uniform(-1, 1, size=(1, *spec.input_shape)).astype(np.float32)

    req = _reference_style_request(spec, X)
    req.model_spec.version.value = 1
    assert predict(req, timeout=20.0).model_spec.version.value == 1

    req = _reference_style_request(spec, X)
    req.model_spec.version.value = 99
    with pytest.raises(grpc.RpcError) as ei:
        predict(req, timeout=20.0)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    assert f"Specific({spec.name}, 99)" in ei.value.details()

    # The OTHER oneof arm: this server defines no version labels, so any
    # label pin is NOT_FOUND -- never silently served the live version.
    req = _reference_style_request(spec, X)
    req.model_spec.version_label = "stable"
    with pytest.raises(grpc.RpcError) as ei:
        predict(req, timeout=20.0)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    assert "stable" in ei.value.details()
