import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import (
    export_model,
    latest_version,
    load_artifact,
    scan_versions,
)
from kubernetes_deep_learning_tpu.export.artifact import version_dir
from kubernetes_deep_learning_tpu.export.inspect import describe
from kubernetes_deep_learning_tpu.models import build_forward, init_variables


@pytest.fixture(scope="module")
def exported_dir(tmp_path_factory, tiny_spec_module):
    root = tmp_path_factory.mktemp("models")
    variables = init_variables(tiny_spec_module, seed=3)
    export_model(tiny_spec_module, variables, str(root), dtype=np.float32)
    return str(root), variables


@pytest.fixture(scope="module")
def tiny_spec_module():
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

    return register_spec(
        ModelSpec(
            name="export-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
        )
    )


def test_versioned_layout_and_scan(exported_dir, tiny_spec_module):
    root, variables = exported_dir
    assert scan_versions(root, tiny_spec_module.name) == [1]
    export_model(tiny_spec_module, variables, root, dtype=np.float32)
    assert scan_versions(root, tiny_spec_module.name) == [1, 2]
    assert latest_version(root, tiny_spec_module.name) == 2


def test_artifact_roundtrip_and_stablehlo_call(exported_dir, tiny_spec_module):
    import jax

    root, variables = exported_dir
    a = load_artifact(version_dir(root, tiny_spec_module.name, 1))
    assert a.spec == tiny_spec_module
    assert a.exported_bytes and a.metadata["platforms"] == ["cpu", "tpu"]

    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(3, 96, 96, 3), dtype=np.uint8)
    got = np.asarray(a.exported.call(a.variables, x))

    fwd = jax.jit(build_forward(tiny_spec_module, dtype=None))
    want = np.asarray(fwd(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_symbolic_batch_dim(exported_dir, tiny_spec_module):
    root, _ = exported_dir
    a = load_artifact(version_dir(root, tiny_spec_module.name, 1))
    for n in (1, 2, 5):
        x = np.zeros((n, 96, 96, 3), np.uint8)
        out = np.asarray(a.exported.call(a.variables, x))
        assert out.shape == (n, 4)


def test_inspector_describe(exported_dir, tiny_spec_module):
    root, _ = exported_dir
    text = describe(version_dir(root, tiny_spec_module.name, 1))
    assert "export-xception" in text
    assert "stablehlo" in text
    assert "(-1, 96, 96, 3)" in text
    assert "params:" in text


def test_exporter_cli(tmp_path):
    from kubernetes_deep_learning_tpu.export.exporter import main as export_main
    from kubernetes_deep_learning_tpu.export.inspect import main as inspect_main

    rc = export_main(
        ["--model", "export-xception", "--output", str(tmp_path), "--dtype", "float32"]
    )
    assert rc == 0
    assert scan_versions(str(tmp_path), "export-xception") == [1]
    assert inspect_main(["--root", str(tmp_path)]) == 0
