from kubernetes_deep_learning_tpu.modelspec import ModelSpec, get_spec


def test_clothing_spec_matches_reference_contract():
    # Contract from reference guide.md:220-231 and model_server.py:21-32.
    spec = get_spec("clothing-model")
    assert spec.input_shape == (299, 299, 3)
    assert spec.num_classes == 10
    assert spec.labels[4] == "pants"
    assert spec.labels == (
        "dress", "hat", "longsleeve", "outwear", "pants",
        "shirt", "shoes", "shorts", "skirt", "t-shirt",
    )
    assert spec.preprocessing == "tf"


def test_spec_json_roundtrip():
    spec = get_spec("clothing-model")
    again = ModelSpec.from_json(spec.to_json())
    assert again == spec


def test_registry_has_baseline_configs():
    # BASELINE.json configs 3 and 4.
    assert get_spec("resnet50-imagenet").family == "resnet50"
    assert get_spec("efficientnet-b3-imagenet").family == "efficientnet-b3"
