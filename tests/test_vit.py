import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models import build_forward, create_model, init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, get_spec, register_spec


@pytest.fixture(scope="module")
def tiny_vit_spec() -> ModelSpec:
    return register_spec(
        ModelSpec(
            name="tiny-vit",
            family="vit-tiny",
            input_shape=(32, 32, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
            description="test-only tiny vit (16 tokens)",
        )
    )


def test_forward_shape_and_dtype(tiny_vit_spec):
    variables = init_variables(tiny_vit_spec, seed=0)
    fwd = build_forward(tiny_vit_spec, dtype=None)
    x = np.zeros((2, *tiny_vit_spec.input_shape), np.uint8)
    logits = jax.jit(fwd)(variables, x)
    assert logits.shape == (2, tiny_vit_spec.num_classes)
    assert logits.dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(logits)))


def test_flash_and_reference_attention_agree(tiny_vit_spec):
    # train=False routes attention through jax.lax.platform_dependent (the
    # Pallas flash kernel on TPU, einsum on CPU); train=True routes through
    # attention_trainable (flash forward + custom-VJP blockwise backward,
    # einsum primal on CPU).  No dropout/batchnorm, so both paths compute
    # the same function and must agree.
    model = create_model(tiny_vit_spec)
    variables = init_variables(tiny_vit_spec, seed=0)
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((2, *tiny_vit_spec.input_shape)), jnp.float32
    )
    infer = model.apply(variables, x, train=False)
    train = model.apply(variables, x, train=True)
    np.testing.assert_allclose(np.asarray(infer), np.asarray(train), atol=1e-4)


def test_vit_short_seq_exports_portable_and_serves(tiny_vit_spec, tmp_path):
    # Since the round-4 shape routing, short-S ViTs (S <= EINSUM_MAX_SEQ)
    # run the platform-portable einsum attention, so export emits ONE
    # portable module -- no per-platform fallback needed -- and the engine
    # serves it.
    import os

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.export.exporter import export_model
    from kubernetes_deep_learning_tpu.runtime.engine import InferenceEngine

    variables = init_variables(tiny_vit_spec, seed=0)
    directory = export_model(tiny_vit_spec, variables, str(tmp_path))
    files = set(os.listdir(directory))
    assert art.MODULE_FILE in files

    a = art.load_artifact(directory)
    engine = InferenceEngine(a, buckets=(1, 2), use_exported=True)
    engine.warmup()
    out = engine.predict(np.zeros((2, *tiny_vit_spec.input_shape), np.uint8))
    assert out.shape == (2, tiny_vit_spec.num_classes)
    assert np.all(np.isfinite(out))


def test_vit_long_seq_exports_per_platform(tmp_path):
    # Past the einsum sequence budget the flash branch is back in the
    # traced module; its platform_dependent cannot co-lower into one
    # cpu+tpu module, so export_model must fall back to one module per
    # platform, and the artifact must load with the per-platform layout.
    import os

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.export.exporter import export_model
    from kubernetes_deep_learning_tpu.ops.attention import EINSUM_MAX_SEQ

    spec = register_spec(
        ModelSpec(
            name="tiny-vit-long",
            family="vit-tiny",
            # patch 8 -> (256/8)^2 = 1024 tokens > EINSUM_MAX_SEQ: the
            # serving attention routes to the flash kernel.
            input_shape=(256, 256, 3),
            labels=("a", "b"),
            preprocessing="tf",
            description="test-only long-sequence vit (1024 tokens)",
        )
    )
    assert (256 // 8) ** 2 > EINSUM_MAX_SEQ

    variables = init_variables(spec, seed=0)
    directory = export_model(spec, variables, str(tmp_path))
    files = set(os.listdir(directory))
    if hasattr(jax, "typeof"):
        # Modern JAX: platform_dependent branches survive into the traced
        # module, cannot co-lower cpu+tpu -> per-platform layout.
        assert art.platform_module_file("cpu") in files
        assert art.platform_module_file("tpu") in files
        assert art.MODULE_FILE not in files
        a = art.load_artifact(directory)
        assert a.metadata["module_layout"] == "per-platform"
        assert a.module_bytes_for("cpu") is not None
    else:
        # Pre-pruning JAX (utils.jaxcompat.platform_dependent): the branch
        # resolves at trace time, so ONE portable module exports -- the
        # layout differs but the artifact must still load and serve.
        a = art.load_artifact(directory)
        assert a.module_bytes_for("cpu") is not None

    # The engine must pick its device's module at load and serve from it
    # (CPU here -> the einsum branch of the platform-dependent module).
    from kubernetes_deep_learning_tpu.runtime.engine import InferenceEngine

    engine = InferenceEngine(a, buckets=(1,), use_exported=True)
    engine.warmup()
    out = engine.predict(np.zeros((1, *spec.input_shape), np.uint8))
    assert out.shape == (1, spec.num_classes)
    assert np.all(np.isfinite(out))


def test_vit_b16_structure():
    spec = get_spec("vit-b16-imagenet")
    model = create_model(spec)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, *spec.input_shape)))
    )
    params = variables["params"]
    # 256x256 / 16 -> 16x16 = 256 tokens, width 768.
    assert params["pos_embed"].shape == (1, 256, 768)
    assert params["head"]["kernel"].shape == (768, 1000)
    assert params["block_11"]["attn"]["query"]["kernel"].shape == (768, 12, 64)


def test_train_step_on_vit(tiny_vit_spec):
    # BN-free family: the train step must run without batch_stats updates.
    import optax

    from kubernetes_deep_learning_tpu.training.trainer import (
        build_train_step,
        create_train_state,
    )

    tx = optax.sgd(1e-3)
    state = create_train_state(tiny_vit_spec, tx, seed=0)
    step = build_train_step(tiny_vit_spec, tx)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(4, *tiny_vit_spec.input_shape), dtype=np.uint8)
    labels = rng.integers(0, tiny_vit_spec.num_classes, size=(4,), dtype=np.int32)
    state, metrics = step(state, images, labels)
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
