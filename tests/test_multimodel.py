"""Multi-model serving end to end: two models in ONE model-server process
(shared scheduler + dispatcher), gateway routing by path and header, the
client's --model surface, per-model metrics -- and the acceptance bar:
logits from concurrent two-model serving are BIT-IDENTICAL to single-model
serving of each.  Real engines on the CPU backend (tiny specs)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest
import requests

from kubernetes_deep_learning_tpu.export import export_model
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.serving import protocol
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

SHAPE = (64, 64, 3)  # tier-1 budget: the smallest shape xception builds at


def _spec(name: str, labels) -> ModelSpec:
    return register_spec(ModelSpec(
        name=name, family="xception", input_shape=SHAPE,
        labels=tuple(labels), preprocessing="tf", resize_filter="nearest",
    ))


@pytest.fixture(scope="module")
def duo(tmp_path_factory):
    """Two exported models under ONE root + the server + gateway stack."""
    spec_a = _spec("mm-alpha", ("dress", "hat", "pants"))
    spec_b = _spec("mm-beta", ("cat", "dog"))
    root = tmp_path_factory.mktemp("models")
    vars_a = init_variables(spec_a, seed=11)
    vars_b = init_variables(spec_b, seed=22)
    export_model(spec_a, vars_a, str(root), dtype=np.float32)
    export_model(spec_b, vars_b, str(root), dtype=np.float32)

    server = ModelServer(str(root), port=0, buckets=(1, 2), max_delay_ms=1.0)
    server.warmup()
    server.start()
    gateway = Gateway(
        serving_host=f"localhost:{server.port}", model=spec_a.name, port=0
    )
    gateway.start()
    yield spec_a, spec_b, root, server, gateway
    gateway.shutdown()
    server.shutdown()


def _predict_direct(server, name, imgs):
    r = requests.post(
        f"http://localhost:{server.port}/v1/models/{name}:predict",
        data=protocol.encode_predict_request(imgs),
        headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
        timeout=30,
    )
    r.raise_for_status()
    return protocol.decode_predict_response(
        r.content, r.headers.get("Content-Type", "")
    )


def test_two_models_bit_identical_to_single_model_serving(duo):
    """The acceptance criterion: each model served CONCURRENTLY from the
    two-model process returns logits bit-identical to a single-model
    server of the same artifact (same buckets, same padding, same
    programs -- the scheduler changes WHO runs next, never WHAT runs)."""
    spec_a, spec_b, root, server, _ = duo
    rng = np.random.default_rng(7)
    imgs = rng.integers(0, 256, size=(2, *SHAPE), dtype=np.uint8)

    # Concurrent requests against both models of the shared process.
    results: dict = {}

    def hit(name):
        results[name] = _predict_direct(server, name, imgs)

    threads = [
        threading.Thread(target=hit, args=(s.name,))
        for s in (spec_a, spec_b)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)

    # Single-model references: the same artifact served alone, through the
    # same execution path (InferenceEngine, same buckets => same compiled
    # programs + padding).  Engine-level rather than a second HTTP server:
    # the wire is already covered above, and the claim under test is about
    # the EXECUTION, which is identical from ServedModel down.
    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.runtime import InferenceEngine

    for spec in (spec_a, spec_b):
        # buckets=(2,): the batch-2 request runs the bucket-2 program on
        # both sides, and that program is identical whether or not bucket
        # 1 also exists -- one compile per reference instead of two.
        solo = InferenceEngine(
            art.load_artifact(
                art.version_dir(str(root), spec.name, 1)
            ),
            buckets=(2,),
        )
        solo.warmup()
        want = solo.predict(imgs)
        got, got_labels = results[spec.name]
        assert got_labels == list(spec.labels)
        np.testing.assert_array_equal(np.asarray(got, np.float32), want)


def test_registry_status_lists_both_models(duo):
    spec_a, spec_b, _, server, _ = duo
    base = f"http://localhost:{server.port}"
    models = requests.get(f"{base}/v1/models", timeout=5).json()
    assert set(models) >= {spec_a.name, spec_b.name}
    for name in (spec_a.name, spec_b.name):
        st = models[name]
        assert st["ready"] is True and st["version"] == 1
        assert st["artifact_hash"]  # the registry's identity key
    # Per-model status endpoint agrees.
    st = requests.get(
        f"{base}/v1/models/{spec_b.name}:status", timeout=5
    ).json()
    assert st == models[spec_b.name]
    assert requests.get(
        f"{base}/v1/models/nope:status", timeout=5
    ).status_code == 404


def test_gateway_routes_by_path_and_header(duo, tmp_path):
    spec_a, spec_b, _, _, gateway = duo
    # Local image host.
    from functools import partial
    from http.server import SimpleHTTPRequestHandler

    rng = np.random.default_rng(3)
    pixels = rng.integers(0, 256, size=(64, 48, 3), dtype=np.uint8)
    from PIL import Image

    Image.fromarray(pixels).save(tmp_path / "img.png")
    httpd = HTTPServer(
        ("127.0.0.1", 0),
        partial(SimpleHTTPRequestHandler, directory=str(tmp_path)),
    )
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}/img.png"
    base = f"http://localhost:{gateway.port}"
    try:
        # Bare /predict -> the default model's label set (back-compat).
        r = requests.post(f"{base}/predict", json={"url": url}, timeout=30)
        assert r.status_code == 200 and set(r.json()) == set(spec_a.labels)
        # Path routing.
        r = requests.post(
            f"{base}/predict/{spec_b.name}", json={"url": url}, timeout=30
        )
        assert r.status_code == 200 and set(r.json()) == set(spec_b.labels)
        # Header routing.
        r = requests.post(
            f"{base}/predict", json={"url": url},
            headers={protocol.MODEL_HEADER: spec_b.name}, timeout=30,
        )
        assert r.status_code == 200 and set(r.json()) == set(spec_b.labels)
        # Unknown model: a clean 404, not a 502 outage costume.
        r = requests.post(
            f"{base}/predict/not-a-model", json={"url": url}, timeout=30
        )
        assert r.status_code == 404
        # Malformed model name: rejected before any upstream is dialed.
        r = requests.post(
            f"{base}/predict/bad%2Fname", json={"url": url}, timeout=30
        )
        assert r.status_code == 404
        # The batch extension routes too.
        r = requests.post(
            f"{base}/predict/{spec_b.name}", json={"urls": [url, url]},
            timeout=30,
        )
        preds = r.json()["predictions"]
        assert len(preds) == 2
        assert all(set(p) == set(spec_b.labels) for p in preds)
    finally:
        httpd.shutdown()


def test_per_model_metrics_on_both_tiers(duo):
    spec_a, spec_b, _, server, gateway = duo
    server_page = requests.get(
        f"http://localhost:{server.port}/metrics", timeout=5
    ).text
    # Bounded `model` label on request counts + pipeline stages + the
    # scheduler lane series (kdlt_batcher_* kept as the invariant name).
    for name in (spec_a.name, spec_b.name):
        assert f'kdlt_model_requests_total{{model="{name}"}}' in server_page
        assert f'model="{name}"' in server_page
    assert 'kdlt_admission_requests_total{tier="model-server",model=' in server_page
    assert "kdlt_sched_dispatch_total" in server_page
    assert "kdlt_pipeline_execute_seconds_count" in server_page
    gw_page = requests.get(
        f"http://localhost:{gateway.port}/metrics", timeout=5
    ).text
    assert "kdlt_model_requests_total" in gw_page


# --- the client's --model surface (satellite regression) -------------------


class _CaptureHandler(BaseHTTPRequestHandler):
    seen: list = []

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):
        type(self).seen.append(
            (self.path, self.headers.get(protocol.MODEL_HEADER))
        )
        body = json.dumps({"ok": 1.0}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_client_default_model_wire_shape_unchanged():
    """kdlt-client without --model must keep the exact legacy wire shape:
    bare /predict, NO X-Kdlt-Model header (the satellite's regression
    bar); --model sets both the path segment and the header."""
    from kubernetes_deep_learning_tpu.serving.client import predict_url

    _CaptureHandler.seen = []
    httpd = HTTPServer(("127.0.0.1", 0), _CaptureHandler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        assert predict_url(base, "http://example/img.png") == {"ok": 1.0}
        assert predict_url(
            base, "http://example/img.png", model="vit"
        ) == {"ok": 1.0}
    finally:
        httpd.shutdown()
    assert _CaptureHandler.seen[0] == ("/predict", None)
    assert _CaptureHandler.seen[1] == ("/predict/vit", "vit")


def test_client_cli_passes_model(monkeypatch, capsys):
    from kubernetes_deep_learning_tpu.serving import client as client_mod

    calls = {}

    def fake_predict_url(gateway, image_url, retries=2, deadline_ms=None,
                         stats=None, model=None, cache_bust=None):
        calls.update(model=model)
        return {"x": 1.0}

    monkeypatch.setattr(client_mod, "predict_url", fake_predict_url)
    assert client_mod.main(["--model", "mm-beta"]) == 0
    assert calls["model"] == "mm-beta"
    assert client_mod.main([]) == 0
    assert calls["model"] is None
