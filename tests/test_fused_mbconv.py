"""Fused MBConv kernel, validated on CPU (interpret mode; conftest.py).

Pinned here: kernel-vs-reference numerics (3x3 and 5x5 taps, sublane-padded
batches), weight extraction + the whole fused block against the REAL
flax.linen MBConvBlock on the same initialized variables.  The real-TPU
speed claim is exp/mbconv_variants.py + BENCH.md's job.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models.efficientnet import MBConvBlock
from kubernetes_deep_learning_tpu.ops.fused_mbconv import (
    fused_mbconv_block,
    mbconv_block_reference,
    mbconv_block_weights,
)


def _random_weights(rng, c_in, expand, k, se):
    c_mid = c_in * expand
    f32 = lambda *s: jnp.asarray(rng.normal(0, 0.15, s), jnp.float32)  # noqa: E731
    return {
        "expand_w": f32(c_in, c_mid).astype(jnp.bfloat16),
        "expand_s": jnp.asarray(rng.uniform(0.8, 1.2, c_mid), jnp.float32),
        "expand_b": f32(c_mid),
        "dw": f32(k, k, c_mid),
        "dw_s": jnp.asarray(rng.uniform(0.8, 1.2, c_mid), jnp.float32),
        "dw_b": f32(c_mid),
        "se_r_w": f32(c_mid, se).astype(jnp.bfloat16),
        "se_r_b": f32(se),
        "se_e_w": f32(se, c_mid).astype(jnp.bfloat16),
        "se_e_b": f32(c_mid),
        "proj_w": f32(c_mid, c_in).astype(jnp.bfloat16),
        "proj_s": jnp.asarray(rng.uniform(0.8, 1.2, c_in), jnp.float32),
        "proj_b": f32(c_in),
    }


@pytest.mark.parametrize(
    "shape,k",
    [
        ((4, 6, 6, 128), 3),
        ((2, 5, 7, 128), 5),
        # non-8-multiple batches run via sublane padding
        ((3, 6, 6, 128), 3),
        ((1, 4, 4, 128), 5),
    ],
)
def test_kernel_matches_reference(shape, k):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, shape), jnp.bfloat16)
    w = _random_weights(rng, shape[-1], expand=2, k=k, se=32)
    want = np.asarray(mbconv_block_reference(x, w), np.float32)
    got = np.asarray(
        jax.jit(lambda x: fused_mbconv_block(x, w, interpret=True))(x), np.float32
    )
    assert got.shape == shape
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 2e-2, f"kernel diverges from reference: {rel:.2e}"


def test_fused_block_matches_flax_mbconv():
    """Weight extraction + kernel vs the real flax MBConvBlock (inference
    BN, expand 6x, SE, residual) on the same initialized variables."""
    rng = np.random.default_rng(2)
    c = 128
    block = MBConvBlock(
        features=c, expand_ratio=6, kernel=3, strides=1,
        se_features=max(1, c // 4), dtype=jnp.bfloat16, name="blk",
    )
    x0 = jnp.asarray(rng.normal(0, 1, (4, 7, 7, c)), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x0, train=False)
    # Realistic (non-init) BN stats so folding is actually exercised.
    stats = jax.tree_util.tree_map(
        lambda a: jnp.asarray(rng.uniform(0.5, 1.5, a.shape), a.dtype),
        variables["batch_stats"],
    )
    variables = {"params": variables["params"], "batch_stats": stats}

    want = np.asarray(
        block.apply(variables, x0.astype(jnp.bfloat16), train=False), np.float32
    )
    w = mbconv_block_weights(
        {"blk": variables["params"]}, {"blk": stats}, "blk"
    )
    got = np.asarray(
        jax.jit(
            lambda x: fused_mbconv_block(x.astype(jnp.bfloat16), w, interpret=True)
        )(x0),
        np.float32,
    )
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 2e-2, f"fused block diverges from flax MBConv: {rel:.2e}"
