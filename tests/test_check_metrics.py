"""tools/check_metrics.py wired into tier-1: the production tree must
stay clean (every metric kdlt_-prefixed, minted via the central helpers),
and the lint itself must actually catch the violations it claims to."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

import check_metrics  # noqa: E402


def test_production_tree_is_clean(capsys):
    assert check_metrics.main() == 0, capsys.readouterr().out


def test_lint_flags_unprefixed_mint():
    src = 'reg.counter("my_requests_total", "oops")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "not kdlt_-prefixed" in v and "my_requests_total" in v


def test_lint_flags_non_literal_name():
    src = 'reg.histogram(name_var, "dynamic")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "non-literal" in v


def test_lint_accepts_kdlt_fstring_head():
    src = 'reg.histogram(f"kdlt_pipeline_{stage}_seconds", "ok")\n'
    assert check_metrics.lint_source(src, "fake.py") == []


def test_lint_flags_model_label_minted_outside_central_module():
    src = 'child = reg.with_labels(model=name, version="1")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "with_labels(model=...)" in v and "central" in v
    # The central module itself is exempt (model_registry lives there)...
    assert check_metrics.lint_source(
        src, os.path.join("kubernetes_deep_learning_tpu", "utils", "metrics.py")
    ) == []
    # ...and other labels stay free.
    assert check_metrics.lint_source(
        'reg.with_labels(tier="gateway")\n', "fake.py"
    ) == []


def test_lint_flags_direct_construction():
    src = (
        "from kubernetes_deep_learning_tpu.utils.metrics import Histogram\n"
        'h = Histogram("kdlt_rogue_seconds")\n'
    )
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "direct Histogram" in v

    src = (
        "from kubernetes_deep_learning_tpu.utils import metrics as m\n"
        'c = m.Counter("kdlt_rogue_total")\n'
    )
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "direct Counter" in v


def test_lint_ignores_unrelated_counter_classes():
    # collections.Counter etc. must not false-positive: only names imported
    # from utils.metrics are metric classes.
    src = (
        "from collections import Counter\n"
        "c = Counter(['a', 'b'])\n"
    )
    assert check_metrics.lint_source(src, "fake.py") == []


def test_lint_exempts_central_module_construction():
    src = 'x = Counter("anything")\n'
    # Inside utils/metrics.py the classes ARE the implementation.
    path = os.path.join("kubernetes_deep_learning_tpu", "utils", "metrics.py")
    assert all(
        "direct" not in v for v in check_metrics.lint_source(src, path)
    )


_METRICS_PATH = os.path.join(
    "kubernetes_deep_learning_tpu", "utils", "metrics.py"
)


def test_lint_flags_slo_series_minted_outside_central_module():
    src = 'reg.gauge("kdlt_slo_burn_rate", "rogue slice")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "kdlt_slo_" in v and "central" in v
    # The central module itself mints the matrix.
    assert check_metrics.lint_source(src, _METRICS_PATH) == []


def test_lint_flags_exemplar_on_non_histogram_mutation():
    (v,) = check_metrics.lint_source(
        'c.inc(1, exemplar="rid")\n', "fake.py"
    )
    assert "exemplar" in v and "histogram" in v
    (v,) = check_metrics.lint_source(
        'g.set(1.0, exemplar="rid")\n', "fake.py"
    )
    assert "exemplar" in v
    # observe() is the sanctioned carrier.
    assert check_metrics.lint_source(
        'h.observe(0.1, exemplar="rid")\n', "fake.py"
    ) == []


def test_lint_flags_cache_series_minted_outside_central_module():
    # The response cache's series (ISSUE 8): kdlt_cache_* mints are
    # confined to utils/metrics.py exactly like kdlt_slo_*.
    src = 'reg.counter("kdlt_cache_hits_total", "rogue mint")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "kdlt_cache_" in v and "central" in v
    assert check_metrics.lint_source(src, _METRICS_PATH) == []


def test_lint_flags_cache_eviction_reason_label_outside_central():
    # The bounded ``reason`` label (cache eviction reasons) may only be
    # attached by the central helpers.
    (v,) = check_metrics.lint_source(
        'reg.with_labels(reason="lru")\n', "fake.py"
    )
    assert "reason" in v and "central" in v
    assert check_metrics.lint_source(
        'reg.with_labels(reason="lru")\n', _METRICS_PATH
    ) == []


def test_lint_flags_bounded_window_and_class_labels_outside_central():
    (v,) = check_metrics.lint_source(
        'reg.with_labels(window="5m")\n', "fake.py"
    )
    assert "window" in v and "central" in v
    # "class" is a reserved word, so it arrives via **{"class": ...}.
    (v,) = check_metrics.lint_source(
        'reg.with_labels(**{"class": "error"})\n', "fake.py"
    )
    assert "class" in v
    # Unbounded labels stay free, and the central module is exempt.
    assert check_metrics.lint_source(
        'reg.with_labels(tier="gateway")\n', "fake.py"
    ) == []
    assert check_metrics.lint_source(
        'reg.with_labels(window="5m")\n', _METRICS_PATH
    ) == []


def test_lint_flags_quant_series_minted_outside_central_module():
    src = 'reg.gauge("kdlt_quant_scheme", "stray")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "kdlt_quant_scheme" in v and "central" in v
    src = 'reg.counter("kdlt_quant_gate_failures_total", "stray")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "central" in v
    # The central module itself mints them.
    assert check_metrics.lint_source(
        'reg.gauge("kdlt_quant_scheme", "ok")\n',
        os.path.join("kubernetes_deep_learning_tpu", "utils", "metrics.py"),
    ) == []


def test_lint_flags_pool_series_minted_outside_central_module():
    # Dynamic-membership series (ISSUE 11): kdlt_pool_* mints are confined
    # to utils/metrics.py exactly like kdlt_slo_*/kdlt_cache_*.
    src = 'reg.counter("kdlt_pool_joins_total", "rogue mint")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "kdlt_pool_" in v and "central" in v
    assert check_metrics.lint_source(src, _METRICS_PATH) == []
    src = 'reg.gauge("kdlt_pool_members", "rogue mint")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "central" in v


def test_lint_flags_warm_source_series_minted_outside_central_module():
    # kdlt_engine_warm_source carries the bounded ``source`` label but
    # lives under the (uncentralizable) kdlt_engine_ prefix, so it is
    # confined by exact name.
    src = 'reg.counter("kdlt_engine_warm_source", "rogue mint")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "kdlt_engine_warm_source" in v and "central" in v
    assert check_metrics.lint_source(src, _METRICS_PATH) == []
    # Sibling kdlt_engine_* names stay mintable where the engine lives.
    assert check_metrics.lint_source(
        'reg.gauge("kdlt_engine_warmup_seconds", "ok")\n', "fake.py"
    ) == []


def test_lint_flags_source_label_outside_central():
    (v,) = check_metrics.lint_source(
        'reg.with_labels(source="cache")\n', "fake.py"
    )
    assert "source" in v and "central" in v
    assert check_metrics.lint_source(
        'reg.with_labels(source="cache")\n', _METRICS_PATH
    ) == []


def test_lint_flags_scheme_label_outside_central():
    src = 'reg.with_labels(scheme="int8-w8a8")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "scheme" in v and "central" in v
    assert check_metrics.lint_source(
        src, os.path.join("kubernetes_deep_learning_tpu", "utils", "metrics.py")
    ) == []


def test_lint_flags_brownout_series_and_labels_outside_central():
    # Brownout ladder series (ISSUE 12): kdlt_brownout_* mints and the
    # bounded stage/direction labels are confined to utils/metrics.py.
    src = 'reg.gauge("kdlt_brownout_stage", "rogue mint")\n'
    (v,) = check_metrics.lint_source(src, "fake.py")
    assert "kdlt_brownout_" in v and "central" in v
    assert check_metrics.lint_source(src, _METRICS_PATH) == []
    (v,) = check_metrics.lint_source(
        'reg.with_labels(stage="3", direction="up")\n', "fake.py"
    )
    assert "direction" in v and "stage" in v and "central" in v
    assert check_metrics.lint_source(
        'reg.with_labels(stage="3", direction="up")\n', _METRICS_PATH
    ) == []
