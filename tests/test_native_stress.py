"""Run the batch-queue concurrency stress harness (native/bq_stress.cc).

The plain build runs here as a correctness invariant check (result
integrity under 16-thread contention, injected failures, tiny-timeout
abandonment, drain-close mid-traffic).  The ThreadSanitizer variant is a
Makefile target (``make -C native stress``) for toolchains that ship the
tsan runtime.
"""

import os
import shutil
import subprocess

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_bq_stress_invariants_hold():
    build = subprocess.run(
        ["make", "-C", NATIVE_DIR, "build/bq_stress"],
        capture_output=True, text=True,
    )
    assert build.returncode == 0, build.stderr
    run = subprocess.run(
        [os.path.join(NATIVE_DIR, "build", "bq_stress")],
        capture_output=True, text=True, timeout=120,
    )
    assert run.returncode == 0, run.stdout + run.stderr
    assert "mismatches=0" in run.stdout