"""Incident flight recorder (utils/flightrecorder.py): trigger hysteresis
and dedup under flapping signals (fake clock), bundle atomicity under
concurrent triggers, dir-cap eviction oldest-first, the ``incident`` trace
retention class, the /debug/ index on both tiers, and the gateway's
cross-replica incident merge.  All device-free."""

from __future__ import annotations

import json
import os
import re
import threading

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
from kubernetes_deep_learning_tpu.serving.client import render_debug_index
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib
from kubernetes_deep_learning_tpu.utils import trace as trace_lib
from kubernetes_deep_learning_tpu.utils.flightrecorder import (
    EVENT_KINDS,
    TRIGGER_RULES,
    FlightRecorder,
    merge_windows,
    parse_triggers,
)


def _metric(text: str, name: str, **labels: str) -> float:
    for m in re.finditer(rf"^{re.escape(name)}(\{{[^}}]*\}})? (\S+)$", text, re.M):
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1) or ""))
        if all(got.get(k) == v for k, v in labels.items()):
            return float(m.group(2))
    return 0.0


class FakeClock:
    """Deterministic monotonic/wall source so dedup-window and hysteresis
    behavior is tested by *advancing time*, not by sleeping through it."""

    def __init__(self, t: float = 1_700_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _recorder(tmp_path=None, *, registry=None, clock=None, **kw):
    clock = clock or FakeClock()
    kw.setdefault("incident_dir", str(tmp_path / "inc") if tmp_path else "")
    kw.setdefault("enabled", True)
    return clock, FlightRecorder(
        "model-server", registry, clock=clock, wall=clock, **kw
    )


# --- timeline + trigger engine ----------------------------------------------


def test_record_rejects_unknown_kinds_and_stamps_events():
    clock, rec = _recorder()
    with pytest.raises(ValueError):
        rec.record("made.up.kind")
    rec.record("pool.join", replica="r1")
    (ev,) = rec.events()
    assert ev["kind"] == "pool.join"
    assert ev["tier"] == "model-server"
    assert ev["t"] == ev["m"] == clock.t
    assert ev["attrs"] == {"replica": "r1"}
    rec.close()


def test_kill_switch_makes_every_hook_a_noop(tmp_path):
    _, rec = _recorder(tmp_path, enabled=False)
    rec.record("dispatch.stall")
    rec.observe_burn(99.0)
    rec.note_shed()
    rec.tick_shed_burst(min_burst=0)
    assert rec.wait_idle(timeout=1.0)
    assert rec.events() == []
    assert rec.index() == []
    rec.close()


def test_every_trigger_rule_fires_on_a_known_event_kind():
    for name, rule in TRIGGER_RULES.items():
        assert rule["fire"] in EVENT_KINDS, name
        assert rule["clear"] is None or rule["clear"] in EVENT_KINDS, name


def test_parse_triggers_grammar_and_unknown_names():
    got = parse_triggers("brownout=2, dispatch-stall")
    assert got == {"brownout": 2.0, "dispatch-stall": None}
    assert parse_triggers("burn-crossing")["burn-crossing"] == 1.0
    with pytest.raises(ValueError):
        parse_triggers("brownout,made-up-trigger")
    with pytest.raises(ValueError):
        parse_triggers("brownout=hot")


def test_flapping_hysteretic_trigger_yields_one_bundle(tmp_path):
    """A brownout ladder climbing 1->2->3 flaps the fire kind three times;
    hysteresis keeps the trigger armed past the dedup window until the
    clearing exit event, so exactly ONE bundle is captured and every
    suppressed repeat is counted."""
    reg = metrics_lib.Registry()
    clock, rec = _recorder(
        tmp_path, registry=reg, triggers="brownout=1", dedup_s=10.0
    )
    rec.record("brownout.enter", stage=1, burn=2.4)   # fires
    rec.record("brownout.enter", stage=2, burn=3.1)   # armed -> suppressed
    clock.advance(60.0)                               # far past dedup
    rec.record("brownout.enter", stage=3, burn=4.0)   # STILL armed
    assert rec.wait_idle()
    assert len(rec.index()) == 1
    assert rec.index()[0]["trigger"] == "brownout"
    text = reg.render()
    assert _metric(text, "kdlt_incident_captures_total", trigger="brownout") == 1
    assert _metric(text, "kdlt_incident_suppressed_total", trigger="brownout") == 2

    # The clearing signal re-arms; a fresh fire past the dedup window is a
    # genuinely new incident and captures a second bundle.
    rec.record("brownout.exit", stage=0, burn=0.4)
    clock.advance(60.0)
    rec.record("brownout.enter", stage=1, burn=2.2)
    assert rec.wait_idle()
    assert len(rec.index()) == 2

    # Cleared but still INSIDE the dedup window: suppressed, not captured.
    rec.record("brownout.exit", stage=0, burn=0.3)
    clock.advance(1.0)
    rec.record("brownout.enter", stage=1, burn=2.9)
    assert rec.wait_idle()
    assert len(rec.index()) == 2
    assert (
        _metric(reg.render(), "kdlt_incident_suppressed_total", trigger="brownout")
        == 3
    )
    rec.close()


def test_dispatch_stall_rearms_on_dedup_window_alone(tmp_path):
    clock, rec = _recorder(tmp_path, triggers="dispatch-stall", dedup_s=10.0)
    rec.record("dispatch.stall", rid="aaaa0001")
    clock.advance(1.0)
    rec.record("dispatch.stall", rid="aaaa0002")  # inside dedup: suppressed
    assert rec.wait_idle()
    assert len(rec.index()) == 1
    clock.advance(30.0)                           # no clear kind exists --
    rec.record("dispatch.stall", rid="aaaa0003")  # the window alone re-arms
    assert rec.wait_idle()
    assert len(rec.index()) == 2
    rec.close()


def test_burn_crossing_is_edge_detected_at_the_trigger_threshold(tmp_path):
    clock, rec = _recorder(tmp_path, triggers="burn-crossing=2.0", dedup_s=5.0)
    assert rec.trigger_threshold("burn-crossing", 1.0) == 2.0
    rec.observe_burn(0.5)   # primes the edge detector
    rec.observe_burn(2.5)   # up-cross -> event + capture
    rec.observe_burn(2.8)   # above but no crossing: no event
    rec.observe_burn(1.0)   # down-cross -> clearing event
    clock.advance(30.0)
    rec.observe_burn(3.0)   # second genuine crossing
    assert rec.wait_idle()
    kinds = [
        (e["kind"], (e.get("attrs") or {}).get("direction"))
        for e in rec.events()
        if e["kind"] == "burn.cross"
    ]
    assert kinds == [("burn.cross", "up"), ("burn.cross", "down"),
                     ("burn.cross", "up")]
    assert len(rec.index()) == 2
    rec.close()


def test_shed_burst_coalesces_ticks(tmp_path):
    _, rec = _recorder(tmp_path)
    for _ in range(12):
        rec.note_shed()
    rec.tick_shed_burst(min_burst=10)
    rec.note_shed()
    rec.tick_shed_burst(min_burst=10)  # only 1 new shed: below the burst bar
    bursts = [e for e in rec.events() if e["kind"] == "shed.burst"]
    assert len(bursts) == 1
    assert bursts[0]["attrs"]["count"] == 12
    rec.close()


# --- bundle capture: atomicity, caps, persistence ---------------------------


def test_concurrent_triggers_write_complete_atomic_bundles(tmp_path):
    """Eight threads fire simultaneously (dedup disabled): every bundle on
    disk must parse as complete JSON with a unique id and no torn .tmp
    leftovers -- the capture worker serializes writes and publishes each
    via os.replace."""
    _, rec = _recorder(tmp_path, triggers="dispatch-stall", dedup_s=0.0)
    barrier = threading.Barrier(8)

    def fire(i: int) -> None:
        barrier.wait()
        rec.record("dispatch.stall", rid=f"cafe{i:04d}")

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rec.wait_idle()
    index = rec.index()
    assert len(index) == 8
    assert len({e["id"] for e in index}) == 8
    names = os.listdir(tmp_path / "inc")
    assert not [n for n in names if n.endswith(".tmp")]
    assert len([n for n in names if n.endswith(".json")]) == 8
    for entry in index:
        with open(entry["path"], encoding="utf-8") as f:
            bundle = json.load(f)
        assert bundle["id"] == entry["id"]
        assert bundle["trigger"] == "dispatch-stall"
        for key in ("events", "snapshots", "traces", "metrics_delta",
                    "captured_at_s", "capture_latency_s"):
            assert key in bundle, key
        stamps = [e["m"] for e in bundle["events"]]
        assert stamps == sorted(stamps)  # timeline is ordered
    rec.close()


def test_dir_count_cap_evicts_oldest_first_and_counts_drops(tmp_path):
    reg = metrics_lib.Registry()
    clock, rec = _recorder(
        tmp_path, registry=reg, triggers="dispatch-stall",
        dedup_s=0.0, max_bundles=3,
    )
    for i in range(6):
        clock.advance(1.0)
        rec.record("dispatch.stall", rid=f"beef{i:04d}")
        assert rec.wait_idle()  # deterministic capture order
    index = rec.index()  # newest first
    assert len(index) == 3
    fired = [e["fired_at_s"] for e in index]
    assert fired == sorted(fired, reverse=True)
    # The three oldest are gone from disk too, not just the index.
    on_disk = {
        n[:-5] for n in os.listdir(tmp_path / "inc") if n.endswith(".json")
    }
    assert on_disk == {e["id"] for e in index}
    text = reg.render()
    assert _metric(
        text, "kdlt_incident_dropped_total", trigger="dispatch-stall"
    ) == 3
    assert _metric(text, "kdlt_incident_open") == 3
    rec.close()


def test_byte_cap_evicts_down_to_at_least_one_bundle(tmp_path):
    clock, rec = _recorder(
        tmp_path, triggers="dispatch-stall", dedup_s=0.0,
        max_bundles=100, max_mb=1e-6,  # cap smaller than any single bundle
    )
    for i in range(3):
        clock.advance(1.0)
        rec.record("dispatch.stall", rid=f"feed{i:04d}")
        assert rec.wait_idle()
    # The byte cap can never evict the LAST bundle: an incident store that
    # deletes the only evidence it holds is worse than an over-budget one.
    assert len(rec.index()) == 1
    rec.close()


def test_restart_reindexes_surviving_bundles_from_disk(tmp_path):
    clock, rec = _recorder(tmp_path, triggers="dispatch-stall", dedup_s=0.0)
    for i in range(2):
        clock.advance(1.0)
        rec.record("dispatch.stall", rid=f"dead{i:04d}")
    assert rec.wait_idle()
    ids = [e["id"] for e in rec.index()]
    rec.close()

    _, reborn = _recorder(tmp_path)  # same dir, fresh process state
    assert [e["id"] for e in reborn.index()] == ids
    bundle = reborn.get(ids[0])      # memory mirror is empty: disk path
    assert bundle is not None and bundle["id"] == ids[0]
    assert reborn.get("inc-nope") is None
    reborn.close()


# --- incident trace retention class -----------------------------------------


def test_capture_pins_causal_traces_against_eviction(tmp_path):
    reg = metrics_lib.Registry()
    tracer = trace_lib.Tracer("model-server", max_traces=8, registry=reg)
    rid = "abcd1234abcd1234"
    tracer.record(rid, "predict", 0.0, 0.05)
    _, rec = _recorder(
        tmp_path, registry=reg, tracer=tracer,
        triggers="dispatch-stall", dedup_s=0.0,
    )
    rec.record("dispatch.stall", rid=rid)
    assert rec.wait_idle()
    (entry,) = rec.index()
    assert entry["traces"] == [rid]
    assert rid in rec.get(entry["id"])["traces"]
    # Pinned ``incident`` class: a storm of routine traces far past the
    # ring capacity must not evict the bundle's causal trace.
    for i in range(32):
        tracer.record(f"{i:016x}", "routine", 0.0, 0.001)
    assert tracer.trace_info(rid) is not None
    assert _metric(
        reg.render(), "kdlt_trace_retained_total", **{"class": "incident"}
    ) >= 1
    # Upgrade-only: nothing can demote an incident-pinned trace.
    tracer.classify(rid, "routine")
    for _ in range(16):
        tracer.record(f"{os.urandom(8).hex()}", "routine", 0.0, 0.001)
    assert tracer.trace_info(rid) is not None
    rec.close()


def test_incident_outranks_every_other_retention_class():
    pri = trace_lib.RETENTION_PRIORITY
    assert pri["incident"] == max(pri.values())


# --- causal windows ----------------------------------------------------------


def test_merge_windows_groups_nearby_incidents_across_origins():
    entries = [
        {"id": "inc-a", "origin": "gateway", "tier": "gateway",
         "trigger": "replica-unhealthy", "fired_at_s": 100.0},
        {"id": "inc-b", "origin": "127.0.0.1:8500", "tier": "model-server",
         "trigger": "dispatch-stall", "fired_at_s": 112.0},
        {"id": "inc-c", "origin": "gateway", "tier": "gateway",
         "trigger": "brownout", "fired_at_s": 500.0},
        {"id": "inc-skip", "origin": "gateway", "trigger": "brownout"},
    ]
    windows = merge_windows(entries, window_s=30.0)
    assert len(windows) == 2
    first = windows[0]
    assert [i["id"] for i in first["incidents"]] == ["inc-a", "inc-b"]
    assert {i["origin"] for i in first["incidents"]} == {
        "gateway", "127.0.0.1:8500"
    }
    assert set(first["triggers"]) == {"replica-unhealthy", "dispatch-stall"}
    assert windows[1]["incidents"][0]["id"] == "inc-c"


# --- through the real tiers ---------------------------------------------------


IMG = np.zeros((1, 32, 32, 3), np.uint8)


def _make_stub_server(name, tmp_path, subdir="models", **kw):
    spec = register_spec(
        ModelSpec(
            name=name, family="xception", input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )
    root = tmp_path / subdir
    art.save_artifact(
        art.version_dir(str(root), spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        str(root), port=0, buckets=(1, 2), max_delay_ms=1.0, host="127.0.0.1",
        engine_factory=lambda a, **ekw: StubEngine(a, **ekw), **kw,
    )
    server.warmup()
    server.start()
    return spec, server


def test_debug_index_served_on_both_tiers(tmp_path):
    requests = pytest.importorskip("requests")
    spec, server = _make_stub_server("inc-index", tmp_path)
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name,
        port=0, bind=False, probe_interval_s=0,
    )
    try:
        r = requests.get(f"http://127.0.0.1:{server.port}/debug/", timeout=5)
        assert r.status_code == 200
        body = r.json()
        assert body["tier"] == "model-server"
        assert "/debug/incidents" in body["routes"]
        assert "/debug/trace/<rid>" in body["routes"]
        gw_index = gw.debug_index()
        assert gw_index["tier"] == "gateway"
        for route in ("/debug/slo", "/debug/brownout", "/debug/pool",
                      "/debug/cache", "/debug/incidents"):
            assert route in gw_index["routes"], route
        # The kdlt-client --stats footer renders this payload directly.
        footer = render_debug_index(gw_index)
        assert footer.startswith("debug index (gateway tier):")
        assert "/debug/incidents" in footer
    finally:
        gw.shutdown()
        server.shutdown()


def test_gateway_merges_replica_bundles_and_serves_them_by_id(tmp_path):
    """The stalled-replica shape end to end: the model tier captures a
    dispatch-stall bundle, the gateway captures its own replica-unhealthy
    bundle, and /debug/incidents on the gateway shows both -- tagged by
    origin, merged into one causal window -- and resolves the REPLICA's
    bundle id even though the gateway never stored it."""
    spec, server = _make_stub_server(
        "inc-merge", tmp_path,
        incident=True, incident_dir=str(tmp_path / "ms-inc"),
    )
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name,
        port=0, bind=False, probe_interval_s=0,
        incident=True, incident_dir=str(tmp_path / "gw-inc"),
    )
    try:
        server.recorder.record("dispatch.stall", rid="cafecafe00000001")
        assert server.recorder.wait_idle()
        gw.recorder.record(
            "pool.unhealthy", replica=f"127.0.0.1:{server.port}"
        )
        assert gw.recorder.wait_idle()

        payload = gw.handle_incidents()
        own = [e for e in payload["incidents"] if e["origin"] == "gateway"]
        assert own and own[0]["trigger"] == "replica-unhealthy"
        (remote_list,) = payload["replicas"].values()
        assert remote_list and remote_list[0]["trigger"] == "dispatch-stall"
        assert remote_list[0]["tier"] == "model-server"

        windows = payload["windows"]
        assert len(windows) == 1
        assert {i["origin"] for i in windows[0]["incidents"]} == {
            "gateway", f"127.0.0.1:{server.port}"
        }
        assert set(windows[0]["triggers"]) == {
            "replica-unhealthy", "dispatch-stall"
        }

        remote_id = remote_list[0]["id"]
        status, body, ctype = gw.handle_incident(remote_id)
        assert status == 200 and ctype == "application/json"
        bundle = json.loads(body)
        assert bundle["id"] == remote_id
        assert bundle["trigger"] == "dispatch-stall"
        status, body, _ = gw.handle_incident("inc-nope")
        assert status == 404
    finally:
        gw.shutdown()
        server.shutdown()
