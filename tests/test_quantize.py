"""int8 weight-only quantization: drift bounds, artifact flow, serving.

The reference has no quantization story at all; this asserts ours end to
end: quantize -> dequantize drift on real model weights, the versioned
artifact handoff (quantized artifact lands as the NEXT version, exactly how
TF-Serving rolls models), and the engine serving int8 weights with bounded
logit drift vs the float artifact.
"""

from __future__ import annotations

import os

import jax
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.export import export_model
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.ops.quantize import (
    dequantize_variables,
    is_quantized,
    quantize_variables,
    write_quantized_version,
)


@pytest.fixture(scope="module")
def q_spec():
    return register_spec(
        ModelSpec(
            name="quant-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
        )
    )


def test_quantize_dequantize_drift(q_spec):
    variables = init_variables(q_spec, seed=1)
    q = quantize_variables(jax.tree_util.tree_map(np.asarray, variables))
    assert is_quantized(q) and not is_quantized(variables)
    deq = jax.device_get(dequantize_variables(q))

    # per-channel int8: worst-case kernel element error <= scale/2
    flat_q, _ = jax.tree_util.tree_flatten_with_path(q)
    orig = variables["params"]["block1_conv2"]["kernel"]
    back = deq["params"]["block1_conv2"]["kernel"]
    absmax = np.abs(np.asarray(orig)).max(axis=(0, 1, 2))
    assert np.abs(np.asarray(orig) - back).max() <= (absmax.max() / 127) * 0.51

    # logits drift bounded on the full model
    fwd = jax.jit(build_forward(q_spec, dtype=None))
    x = np.random.default_rng(0).integers(0, 256, (2, *q_spec.input_shape), np.uint8)
    a = np.asarray(fwd(variables, x))
    b = np.asarray(fwd(deq, x))
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert rel < 5e-2, f"quantization drift too large: {rel:.3f}"


def test_small_kernels_stay_float(q_spec):
    variables = jax.tree_util.tree_map(np.asarray, init_variables(q_spec, seed=0))
    q = quantize_variables(variables)
    # the 4-class logits head is tiny -> untouched
    head = q["params"]["head"]["logits"]["kernel"]
    assert not isinstance(head, dict)
    # a big pointwise conv is quantized
    pw = q["params"]["block5_sepconv1"]["pointwise"]["kernel"]
    assert isinstance(pw, dict) and pw["_q8"].dtype == np.int8


def test_quantized_artifact_version_flow_and_serving(q_spec, tmp_path):
    from kubernetes_deep_learning_tpu.runtime import InferenceEngine

    variables = init_variables(q_spec, seed=2)
    root = str(tmp_path)
    export_model(q_spec, variables, root, dtype=np.float32)
    path = write_quantized_version(root, q_spec.name)
    assert art.latest_version(root, q_spec.name) == 2
    # quantized artifacts are live-jit only and ~4x smaller on disk
    assert not any(f.endswith(".stablehlo") for f in os.listdir(path))
    v1 = os.path.getsize(
        os.path.join(art.version_dir(root, q_spec.name, 1), art.PARAMS_FILE)
    )
    v2 = os.path.getsize(os.path.join(path, art.PARAMS_FILE))
    assert v2 < v1 / 3

    with pytest.raises(ValueError, match="already quantized"):
        write_quantized_version(root, q_spec.name)

    float_engine = InferenceEngine(
        art.load_artifact(art.version_dir(root, q_spec.name, 1)), buckets=(2,)
    )
    quant_engine = InferenceEngine(art.load_artifact(path), buckets=(2,))
    x = np.random.default_rng(1).integers(0, 256, (2, *q_spec.input_shape), np.uint8)
    a = float_engine.predict(x)
    b = quant_engine.predict(x)
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert rel < 5e-2, f"served quantized logits drift: {rel:.3f}"
