"""int8 weight-only quantization: drift bounds, artifact flow, serving.

The reference has no quantization story at all; this asserts ours end to
end: quantize -> dequantize drift on real model weights, the versioned
artifact handoff (quantized artifact lands as the NEXT version, exactly how
TF-Serving rolls models), and the engine serving int8 weights with bounded
logit drift vs the float artifact.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.export import export_model
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.ops import quantize
from kubernetes_deep_learning_tpu.ops.quantize import (
    dequantize_variables,
    is_quantized,
    quantize_variables,
    write_quantized_version,
)


@pytest.fixture(scope="module")
def q_spec():
    return register_spec(
        ModelSpec(
            name="quant-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
        )
    )


def test_quantize_dequantize_drift(q_spec):
    variables = init_variables(q_spec, seed=1)
    q = quantize_variables(jax.tree_util.tree_map(np.asarray, variables))
    assert is_quantized(q) and not is_quantized(variables)
    deq = jax.device_get(dequantize_variables(q))

    # per-channel int8: worst-case kernel element error <= scale/2
    flat_q, _ = jax.tree_util.tree_flatten_with_path(q)
    orig = variables["params"]["block1_conv2"]["kernel"]
    back = deq["params"]["block1_conv2"]["kernel"]
    absmax = np.abs(np.asarray(orig)).max(axis=(0, 1, 2))
    assert np.abs(np.asarray(orig) - back).max() <= (absmax.max() / 127) * 0.51

    # logits drift bounded on the full model
    fwd = jax.jit(build_forward(q_spec, dtype=None))
    x = np.random.default_rng(0).integers(0, 256, (2, *q_spec.input_shape), np.uint8)
    a = np.asarray(fwd(variables, x))
    b = np.asarray(fwd(deq, x))
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert rel < 5e-2, f"quantization drift too large: {rel:.3f}"


def test_small_kernels_stay_float(q_spec):
    variables = jax.tree_util.tree_map(np.asarray, init_variables(q_spec, seed=0))
    q = quantize_variables(variables)
    # the 4-class logits head is tiny -> untouched
    head = q["params"]["head"]["logits"]["kernel"]
    assert not isinstance(head, dict)
    # a big pointwise conv is quantized
    pw = q["params"]["block5_sepconv1"]["pointwise"]["kernel"]
    assert isinstance(pw, dict) and pw["_q8"].dtype == np.int8


def test_quantized_artifact_version_flow_and_serving(q_spec, tmp_path):
    from kubernetes_deep_learning_tpu.runtime import InferenceEngine

    variables = init_variables(q_spec, seed=2)
    root = str(tmp_path)
    export_model(q_spec, variables, root, dtype=np.float32)
    path = write_quantized_version(root, q_spec.name)
    assert art.latest_version(root, q_spec.name) == 2
    # quantized artifacts are live-jit only and ~4x smaller on disk
    assert not any(f.endswith(".stablehlo") for f in os.listdir(path))
    v1 = os.path.getsize(
        os.path.join(art.version_dir(root, q_spec.name, 1), art.PARAMS_FILE)
    )
    v2 = os.path.getsize(os.path.join(path, art.PARAMS_FILE))
    assert v2 < v1 / 3

    with pytest.raises(ValueError, match="already quantized"):
        write_quantized_version(root, q_spec.name)

    float_engine = InferenceEngine(
        art.load_artifact(art.version_dir(root, q_spec.name, 1)), buckets=(2,)
    )
    quant_engine = InferenceEngine(art.load_artifact(path), buckets=(2,))
    x = np.random.default_rng(1).integers(0, 256, (2, *q_spec.input_shape), np.uint8)
    a = float_engine.predict(x)
    b = quant_engine.predict(x)
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert rel < 5e-2, f"served quantized logits drift: {rel:.3f}"


# --- activation calibration + the w8a8 path (ISSUE 9) -----------------------
#
# CPU-economy note: XLA:CPU has no vectorized s8xs8 conv (the int8 program
# is a slow reference lowering), so these tests quantize only the largest
# kernels (high min_size) at a tiny input size -- the machinery exercised
# (calibration, scale storage, the int8 x int8 -> int32 forward, the
# warmup tolerance gate) is exactly the production path; only the layer
# count is trimmed.

W8A8_MIN_SIZE = 700_000  # the three biggest exit-flow pointwise kernels


@pytest.fixture(scope="module")
def w8a8_spec():
    return register_spec(
        ModelSpec(
            name="w8a8-xception",
            family="xception",
            input_shape=(32, 32, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
        )
    )


@pytest.fixture(scope="module")
def w8a8_artifacts(w8a8_spec, tmp_path_factory):
    """(root, float variables): a float artifact at v1 and a calibrated
    int8-w8a8 artifact at v2, built through the real artifact-build path."""
    root = str(tmp_path_factory.mktemp("w8a8-models"))
    variables = jax.tree_util.tree_map(
        np.asarray, init_variables(w8a8_spec, seed=3)
    )
    art.save_artifact(
        art.version_dir(root, w8a8_spec.name, 1), w8a8_spec, variables, None,
        {"compute_dtype": "float32"},
    )
    calib = np.random.default_rng(7).integers(
        0, 256, size=(16, *w8a8_spec.input_shape), dtype=np.uint8
    )
    # percentile=100 (absmax): the calibration stream here is uniform
    # noise with no outliers, so the clip would only add clip error --
    # the production default (99.9) is for real traffic's tail.
    quantize.write_quantized_version(
        root, w8a8_spec.name, scheme=quantize.SCHEME_W8A8,
        calib_images=calib, min_size=W8A8_MIN_SIZE, percentile=100.0,
    )
    return root, variables


def test_clip_scale_floor_on_zero_range_stream():
    # A dead layer's calibration stream is identically zero: the scale
    # must floor to a finite positive value, never 0 (divide-by-zero in
    # the quantize-in step would be a NaN factory).
    s = quantize.clip_scale(np.zeros(1000, np.float32))
    assert float(s) > 0 and np.isfinite(s)
    # And quantizing by it stays finite.
    q = np.clip(np.round(np.zeros(8, np.float32) / s), -127, 127)
    assert np.isfinite(q).all() and (q == 0).all()


def test_clip_scale_percentile_vs_absmax_on_outlier_stream():
    # 10k well-behaved samples <= 1.0 plus ONE 1000.0 outlier: absmax
    # calibration (percentile=100) stretches the scale ~1000x, collapsing
    # the typical values into a handful of int8 codes; the percentile clip
    # keeps resolution where the mass is.
    rng = np.random.default_rng(0)
    stream = np.abs(rng.normal(0.2, 0.2, size=10_000)).clip(0, 1.0)
    stream[1234] = 1000.0
    s_absmax = quantize.clip_scale(stream, percentile=100.0)
    s_clip = quantize.clip_scale(stream, percentile=99.9)
    assert float(s_absmax) == pytest.approx(1000.0 / 127.0, rel=1e-3)
    assert float(s_clip) <= 2.0 / 127.0  # near the true mass, not the outlier
    # Quantize/dequantize the typical values under both scales: the clip
    # must reconstruct the mass far better (under absmax, nearly every
    # typical value rounds to code 0 and is lost entirely).
    typical = stream[stream <= 1.0]

    def mean_recon_err(scale):
        q = np.clip(np.round(typical / scale), -127, 127)
        return float(np.abs(q * scale - typical).mean())

    assert mean_recon_err(s_clip) < mean_recon_err(s_absmax) / 10


def test_calibration_scheme_roundtrip_msgpack(w8a8_spec, w8a8_artifacts):
    root, _ = w8a8_artifacts
    loaded = art.load_artifact(art.version_dir(root, w8a8_spec.name, 2))
    assert loaded.metadata["quantization"] == quantize.SCHEME_W8A8
    assert loaded.metadata["calibration"]["layers"] >= 2
    scales = quantize.activation_scales(loaded.variables)
    assert quantize.is_calibrated(loaded.variables)
    assert len(scales) == loaded.metadata["calibration"]["layers"]
    for path, s in scales.items():
        v = np.asarray(s)
        assert v.dtype == np.float32 and np.isfinite(v) and v > 0, path
    # No StableHLO: quantized artifacts are live-jit only.
    assert loaded.exported_bytes is None and not loaded.platform_modules


def test_scheme_survives_registry_hot_reload(w8a8_spec, w8a8_artifacts):
    # The version watcher's scan/swap path must carry the scheme tag: a
    # w8a8 artifact dropped as the next version hot-reloads with its
    # quantization visible on the status surface (the engine dispatches
    # on the same metadata).
    from types import SimpleNamespace

    from kubernetes_deep_learning_tpu.serving.registry import ModelRegistry

    root, _ = w8a8_artifacts
    seen = []

    def loader(name, version, directory):
        a = art.load_artifact(directory)
        seen.append((version, a.metadata.get("quantization")))
        return SimpleNamespace(
            version=version, artifact=a,
            engine=SimpleNamespace(ready=True, buckets=(1,)),
        )

    reg = ModelRegistry(root, loader=loader)
    reg.poll()
    # v2 (the quantized artifact) is the highest version; one load.
    assert seen == [(2, quantize.SCHEME_W8A8)]
    status = reg.model_status(w8a8_spec.name)
    assert status["version"] == 2
    assert status["quantization"] == quantize.SCHEME_W8A8
    assert status["quantization_active"] == quantize.SCHEME_W8A8


# The three engine-level w8a8 tests below compile int8 programs (slow on
# XLA:CPU's reference lowering) and so ride the slow marker, like the
# other PRs' acceptance bars (cache-ab, crosshost-ab); the cheap tier-1
# coverage above still exercises calibration, storage, and hot reload.
@pytest.mark.slow
def test_w8a8_engine_serves_within_tolerance(w8a8_spec, w8a8_artifacts):
    from kubernetes_deep_learning_tpu.runtime import InferenceEngine

    root, variables = w8a8_artifacts
    eng = InferenceEngine(
        art.load_artifact(art.version_dir(root, w8a8_spec.name, 2)),
        buckets=(2,),
    )
    assert eng.quantization == quantize.SCHEME_W8A8
    eng.warmup()  # includes the tolerance gate
    assert eng.quantization_active == quantize.SCHEME_W8A8
    assert not eng.quant_gate_failed
    assert 0 <= eng.quant_gate_drift <= quantize.resolve_quant_tol()
    x = np.random.default_rng(1).integers(
        0, 256, (2, *w8a8_spec.input_shape), np.uint8
    )
    got = eng.predict(x)
    fwd = jax.jit(build_forward(w8a8_spec, dtype=np.float32, fast=False))
    want = np.asarray(fwd(variables, x))
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 0.1, f"served w8a8 logits drift: {rel:.3f}"
    assert (got.argmax(-1) == want.argmax(-1)).all()


@pytest.mark.slow
def test_gate_refuses_miscalibrated_artifact(w8a8_spec, w8a8_artifacts):
    # A deliberately mis-calibrated artifact (activation scales x1000: the
    # classic stale-calibration failure) must refuse w8a8 activation at
    # warmup, fall back to weight-only serving, and count the gate failure
    # -- while still serving correct-shape (weight-only-accurate) logits.
    from kubernetes_deep_learning_tpu.runtime import InferenceEngine

    root, variables = w8a8_artifacts
    artifact = art.load_artifact(art.version_dir(root, w8a8_spec.name, 2))

    def corrupt(tree):
        if isinstance(tree, dict):
            if quantize.ACT_SCALE_KEY in tree:
                return {
                    **tree,
                    quantize.ACT_SCALE_KEY: np.float32(
                        np.asarray(tree[quantize.ACT_SCALE_KEY]) * 1e3
                    ),
                }
            return {k: corrupt(v) for k, v in tree.items()}
        return tree

    import dataclasses

    bad = dataclasses.replace(artifact, variables=corrupt(artifact.variables))
    eng = InferenceEngine(bad, buckets=(2,))
    eng.warmup()
    assert eng.quant_gate_failed
    assert eng.quantization == quantize.SCHEME_W8A8
    assert eng.quantization_active == quantize.SCHEME  # weight-only fallback
    assert eng._m_quant["gate_failures"].value == 1.0
    # The active-scheme gauge follows the DOWNGRADED scheme.
    assert eng._m_quant["scheme"][quantize.SCHEME].value == 1.0
    assert eng._m_quant["scheme"][quantize.SCHEME_W8A8].value == 0.0
    # And the fallback serves the weight-only numerics, unaffected by the
    # corrupted activation scales.
    x = np.random.default_rng(2).integers(
        0, 256, (2, *w8a8_spec.input_shape), np.uint8
    )
    got = eng.predict(x)
    fwd = jax.jit(build_forward(w8a8_spec, dtype=np.float32, fast=False))
    want = np.asarray(fwd(variables, x))
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 5e-2


@pytest.mark.slow
def test_scheme_override_env_forces_weight_only(
    w8a8_spec, w8a8_artifacts, monkeypatch
):
    # $KDLT_QUANT_SCHEME=weight-only: the fleet-wide rollback knob refuses
    # int8 activations WITHOUT touching the artifact (no gate run, no
    # failure counted -- this is an operator choice, not a defect).
    from kubernetes_deep_learning_tpu.runtime import InferenceEngine

    monkeypatch.setenv(quantize.QUANT_SCHEME_ENV, "weight-only")
    root, _ = w8a8_artifacts
    eng = InferenceEngine(
        art.load_artifact(art.version_dir(root, w8a8_spec.name, 2)),
        buckets=(1,),
    )
    assert eng.quantization == quantize.SCHEME_W8A8
    assert eng.quantization_active == quantize.SCHEME
    eng.warmup()
    assert not eng.quant_gate_failed
    assert eng._m_quant["gate_failures"].value == 0.0


@pytest.mark.slow
def test_gate_failure_e2e_over_model_server(w8a8_spec, w8a8_artifacts, tmp_path):
    # The acceptance e2e: a mis-calibrated artifact served through the REAL
    # model server refuses w8a8 at warmup, serves weight-only, surfaces
    # both schemes on /v1/models, and counts the failure on /metrics --
    # while predicts keep working.
    import dataclasses
    import urllib.request

    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    root, _ = w8a8_artifacts
    artifact = art.load_artifact(art.version_dir(root, w8a8_spec.name, 2))

    def corrupt(tree):
        if isinstance(tree, dict):
            if quantize.ACT_SCALE_KEY in tree:
                return {
                    **tree,
                    quantize.ACT_SCALE_KEY: np.float32(
                        np.asarray(tree[quantize.ACT_SCALE_KEY]) * 1e3
                    ),
                }
            return {k: corrupt(v) for k, v in tree.items()}
        return tree

    bad = dataclasses.replace(artifact, variables=corrupt(artifact.variables))
    bad_root = str(tmp_path / "bad-models")
    art.save_artifact(
        art.version_dir(bad_root, w8a8_spec.name, 1), bad.spec, bad.variables,
        None, bad.metadata,
    )
    server = ModelServer(
        bad_root, port=0, buckets=(2,), host="127.0.0.1",
    )
    server.warmup()
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/v1/models", timeout=10) as r:
            status = json.loads(r.read())[w8a8_spec.name]
        assert status["quantization"] == quantize.SCHEME_W8A8
        assert status["quantization_active"] == quantize.SCHEME
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            page = r.read().decode()
        assert "kdlt_quant_gate_failures_total" in page
        (line,) = [
            ln for ln in page.splitlines()
            if ln.startswith("kdlt_quant_gate_failures_total")
        ]
        assert line.split()[-1] == "1.0"
        # The weight-only fallback actually serves.
        from kubernetes_deep_learning_tpu.serving import protocol

        x = np.random.default_rng(0).integers(
            0, 256, (2, *w8a8_spec.input_shape), np.uint8
        )
        req = urllib.request.Request(
            f"{base}/v1/models/{w8a8_spec.name}:predict",
            data=protocol.encode_predict_request(x),
            headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
    finally:
        server.shutdown()


def test_representative_images_noise_and_dir(tmp_path, w8a8_spec):
    # Seeded noise: deterministic, right shape/dtype.
    a = quantize.representative_images(w8a8_spec, 4, seed=9)
    b = quantize.representative_images(w8a8_spec, 4, seed=9)
    assert a.shape == (4, *w8a8_spec.input_shape) and a.dtype == np.uint8
    assert np.array_equal(a, b)
    # Real-image route: files are loaded, resized to the spec's input
    # shape, and cycled when fewer than n.
    from PIL import Image

    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    Image.fromarray(
        np.random.default_rng(0).integers(0, 256, (50, 40, 3), np.uint8)
    ).save(img_dir / "one.png")
    out = quantize.representative_images(
        w8a8_spec, 3, image_dir=str(img_dir)
    )
    assert out.shape == (3, *w8a8_spec.input_shape)
    assert np.array_equal(out[0], out[1])  # one file, cycled
    with pytest.raises(FileNotFoundError):
        quantize.representative_images(
            w8a8_spec, 1, image_dir=str(tmp_path / "empty-missing")
        )


@pytest.mark.slow
def test_exporter_calibrate_flag_builds_w8a8_next_version(
    w8a8_spec, tmp_path
):
    # kdlt-export --calibrate: the export-layer build step -- float vN
    # plus a calibrated int8-w8a8 vN+1, in one invocation.
    from kubernetes_deep_learning_tpu.export import exporter

    root = str(tmp_path / "export-root")
    rc = exporter.main([
        "--model", w8a8_spec.name, "--output", root, "--seed", "5",
        "--dtype", "float32", "--calibrate", "4",
        "--calibrate-percentile", "100",
    ])
    assert rc == 0
    assert art.scan_versions(root, w8a8_spec.name) == [1, 2]
    v2 = art.load_artifact(art.version_dir(root, w8a8_spec.name, 2))
    assert v2.metadata["quantization"] == quantize.SCHEME_W8A8
    assert v2.metadata["calibration"]["images"] == 4
    assert quantize.is_calibrated(v2.variables)
