"""Dynamic replica membership (serving/upstream.py, ISSUE 11): joiners
quarantined until their first /readyz 200, leavers drained without dropping
in-flight work, DNS-flap spec-memo restore, power-of-two-choices selection,
prober lifecycle (no leaked threads or stale per-replica series), and the
drain-ordering contract (readiness flips BEFORE in-flight completion).
All device-free."""

from __future__ import annotations

import http.server
import re
import threading
import time

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
from kubernetes_deep_learning_tpu.serving.upstream import UpstreamPool
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib


def _metric(text: str, name: str, **labels: str) -> float:
    for m in re.finditer(rf"^{re.escape(name)}(\{{[^}}]*\}})? (\S+)$", text, re.M):
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1) or ""))
        if all(got.get(k) == v for k, v in labels.items()):
            return float(m.group(2))
    raise AssertionError(f"no sample {name} with {labels} in:\n{text}")


class _StatusServer:
    """Minimal health endpoint whose /readyz and /healthz status codes the
    test scripts directly -- a replica's health surface without a replica."""

    def __init__(self):
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                code = outer.codes.get(self.path, 404)
                self.send_response(code)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        self.codes = {"/readyz": 200, "/healthz": 200}
        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        self.host = f"127.0.0.1:{self._httpd.server_address[1]}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


@pytest.fixture
def status_server():
    s = _StatusServer()
    yield s
    s.close()


def _make_stub_server(name, tmp_path, subdir="models", device_ms=0.0, **kw):
    spec = register_spec(
        ModelSpec(
            name=name,
            family="xception",  # never instantiated by StubEngine
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )
    root = tmp_path / subdir
    art.save_artifact(
        art.version_dir(str(root), spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        str(root), port=kw.pop("port", 0), buckets=kw.pop("buckets", (1, 2)),
        max_delay_ms=1.0, host="127.0.0.1",
        engine_factory=lambda a, **ekw: StubEngine(
            a, device_ms_per_batch=device_ms, **ekw
        ),
        **kw,
    )
    server.warmup()
    server.start()
    return spec, server


IMG = np.zeros((1, 32, 32, 3), np.uint8)


# --- membership deltas -------------------------------------------------------


def test_joiner_quarantined_until_first_readyz_200(status_server):
    pool = UpstreamPool(["h1:1"], failover=True, probe_interval_s=0)
    delta = pool.set_membership(["h1:1", status_server.host])
    assert delta == {"joined": [status_server.host], "left": []}
    joiner = pool.replicas[1]
    assert joiner.quarantined and not joiner.routable
    # Invisible to selection: every pick lands on the incumbent.
    incumbent = pool.replicas[0]
    assert all(pool.choose() is incumbent for _ in range(4))
    # Not even reachable as last-resort fallback (unlike plain unhealthy).
    assert pool.choose(exclude=[incumbent]) is None
    # Warming pod: /readyz not yet 200 -> quarantine holds.
    status_server.codes["/readyz"] = 503
    pool.probe_once()
    assert joiner.quarantined
    # First /readyz 200 releases it into rotation.
    status_server.codes["/readyz"] = 200
    pool.probe_once()
    assert not joiner.quarantined and joiner.routable
    assert joiner in {pool.choose() for _ in range(4)}


def test_blind_mode_joiners_skip_quarantine():
    # KDLT_FAILOVER=0 has no prober to release a quarantine; joiners go
    # straight into the blind rotation.
    pool = UpstreamPool(["h1:1"], failover=False, probe_interval_s=0)
    pool.set_membership(["h1:1", "h2:2"])
    assert not pool.replicas[1].quarantined
    assert {pool.choose() for _ in range(4)} == set(pool.replicas)


def test_empty_view_refused_and_noop_delta():
    pool = UpstreamPool(["h1:1", "h2:2"], failover=True, probe_interval_s=0)
    # A DNS outage resolving to nothing must not dump the fleet.
    assert pool.set_membership([]) == {"joined": [], "left": []}
    assert [r.host for r in pool.replicas] == ["h1:1", "h2:2"]
    # Same view again: no churn counted.
    pool.set_membership(["h2:2", "h1:1"])
    assert pool.joins == 0 and pool.leaves == 0


def test_leave_keeps_incumbent_state_and_retires_series():
    registry = metrics_lib.Registry()
    pool = UpstreamPool(
        ["h1:1", "h2:2"], registry=registry, failover=True, probe_interval_s=0
    )
    keeper = pool.replicas[0]
    keeper.note_latency(0.05)  # state that must survive the delta
    delta = pool.set_membership(["h1:1"])
    assert delta == {"joined": [], "left": ["h2:2"]}
    assert pool.replicas == [keeper]
    assert keeper.ewma_ms == pytest.approx(50.0)
    text = registry.render()
    assert _metric(text, "kdlt_pool_members") == 1.0
    assert _metric(text, "kdlt_pool_leaves_total") == 1.0
    # The departed replica's per-replica series are retired, not left
    # stale on /metrics.
    assert 'replica="h2:2"' not in text
    assert _metric(text, "kdlt_pool_pick_total", replica="h1:1") >= 0.0


def test_dns_flap_restores_memoized_spec(status_server):
    pool = UpstreamPool(
        ["h1:1", status_server.host], failover=True, probe_interval_s=0
    )
    flapper = pool.replicas[1]
    sentinel, extra = object(), object()
    flapper.spec = sentinel
    flapper.specs = {"other-model": extra}
    # The endpoint drops out of DNS...
    pool.set_membership(["h1:1"])
    assert len(pool.replicas) == 1
    # ...and flaps back: re-added quarantined, spec not yet restored.
    pool.set_membership(["h1:1", status_server.host])
    readded = pool.replicas[1]
    assert readded is not flapper and readded.quarantined
    assert readded.spec is None
    # Quarantine release restores the memoized contracts instead of
    # re-paying discovery (per-request validation still guards staleness).
    pool.probe_once()
    assert not readded.quarantined
    assert readded.spec is sentinel
    assert readded.specs == {"other-model": extra}
    # The memo is consumed: a later rejoin re-discovers.
    assert status_server.host not in pool._spec_memo


def test_spec_memo_is_bounded():
    from kubernetes_deep_learning_tpu.serving.upstream import SPEC_MEMO_CAP

    pool = UpstreamPool(["h1:1"], failover=True, probe_interval_s=0)
    for i in range(SPEC_MEMO_CAP + 10):
        host = f"flap{i}:9"
        pool.set_membership(["h1:1", host])
        pool.replicas[1].spec = object()
        pool.set_membership(["h1:1"])
    assert len(pool._spec_memo) == SPEC_MEMO_CAP
    assert "flap0:9" not in pool._spec_memo  # oldest fell off first


def test_resolve_now_applies_injected_resolver_delta():
    view = ["h1:1", "h2:2"]
    pool = UpstreamPool(
        ["h1:1", "h2:2"], failover=True, probe_interval_s=0,
        resolver=lambda: list(view), resolve_interval_s=0,
    )
    # An explicit resolver implies dynamic membership even without
    # KDLT_POOL_RESOLVE_S: the default cadence applies.
    assert pool.resolve_interval_s > 0
    view.append("h3:3")
    assert pool.resolve_now() == {"joined": ["h3:3"], "left": []}
    view.remove("h1:1")
    assert pool.resolve_now() == {"joined": [], "left": ["h1:1"]}
    assert [r.host for r in pool.replicas] == ["h2:2", "h3:3"]
    # A resolver blip (exception) is treated as an empty view: refused.
    def boom():
        raise OSError("dns down")

    pool.resolver = boom
    assert pool.resolve_now() == {"joined": [], "left": []}
    assert len(pool.replicas) == 2


# --- power-of-two-choices selection ------------------------------------------


def test_p2c_prefers_lighter_ewma_replica():
    pool = UpstreamPool(["h1:1", "h2:2"], failover=True, probe_interval_s=0)
    heavy, light = pool.replicas
    for _ in range(5):
        heavy.note_latency(0.100)
        light.note_latency(0.010)
    # Both routable, rigged EWMAs: the lighter one wins EVERY pick (in a
    # two-replica pool both are always the two choices).
    assert all(pool.choose() is light for _ in range(6))
    # The signal is live: the light replica slowing past the heavy one
    # flips the preference within a few samples.
    for _ in range(20):
        light.note_latency(0.500)
    assert pool.choose() is heavy


def test_p2c_unsampled_replica_ranks_lightest():
    # A joiner with no latency samples must RECEIVE traffic to earn them;
    # ranking it heaviest would starve it forever.
    pool = UpstreamPool(["h1:1", "h2:2"], failover=True, probe_interval_s=0)
    sampled, fresh = pool.replicas
    sampled.note_latency(0.005)  # even a FAST sampled replica
    assert all(pool.choose() is fresh for _ in range(4))


def test_p2c_no_signal_degrades_to_round_robin():
    # The PR 3 contract test_pool_round_robins_and_prefers_healthy relies
    # on: a signal-less pool is exactly the old rotation.
    pool = UpstreamPool(["h1:1", "h2:2"], failover=True, probe_interval_s=0)
    a, b = pool.replicas
    assert [pool.choose() for _ in range(4)] == [a, b, a, b]


# --- prober lifecycle --------------------------------------------------------


def _prober_threads():
    return [
        t for t in threading.enumerate() if t.name == "kdlt-upstream-prober"
    ]


def test_close_stops_prober_thread_and_is_restartable():
    before = len(_prober_threads())
    pool = UpstreamPool(
        ["h1:1", "h2:2"], failover=True, probe_interval_s=0.05
    )
    pool.start_probing()
    pool.start_probing()  # idempotent: still one thread
    assert len(_prober_threads()) == before + 1
    pool.close()
    assert pool._probe_thread is None
    deadline = time.monotonic() + 2.0
    while len(_prober_threads()) > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(_prober_threads()) == before, "close() leaked the prober"
    # Restartable: a stopped pool can start probing again (gateway restart
    # paths construct-once, start/stop many).
    pool.start_probing()
    assert len(_prober_threads()) == before + 1
    pool.close()


def test_single_replica_pool_with_resolver_still_probes():
    # One replica alone needs no prober -- unless dynamic membership could
    # add a second at any tick.
    static = UpstreamPool(["h1:1"], failover=True, probe_interval_s=0.05)
    static.start_probing()
    assert static._probe_thread is None
    dynamic = UpstreamPool(
        ["h1:1"], failover=True, probe_interval_s=0.05,
        resolver=lambda: ["h1:1"], resolve_interval_s=0.05,
    )
    dynamic.start_probing()
    assert dynamic._probe_thread is not None
    dynamic.close()


def test_churn_does_not_leak_series_or_duplicate_on_flap():
    registry = metrics_lib.Registry()
    pool = UpstreamPool(
        ["h1:1"], registry=registry, failover=True, probe_interval_s=0
    )
    for _ in range(5):  # the same endpoint flapping in and out
        pool.set_membership(["h1:1", "flap:9"])
        pool.set_membership(["h1:1"])
    text = registry.render()
    assert 'replica="flap:9"' not in text  # every leave retired its series
    assert len(re.findall(r'kdlt_pool_pick_total\{[^}]*"h1:1"', text)) == 1
    assert _metric(text, "kdlt_pool_joins_total") == 5.0
    assert _metric(text, "kdlt_pool_leaves_total") == 5.0
    assert _metric(text, "kdlt_pool_members") == 1.0


# --- drain ordering + leave-under-load through the real tiers ----------------


def test_drain_flips_readyz_before_inflight_completion(tmp_path):
    """Satellite 2 (ISSUE 11): a SIGTERM'd model server leaves rotation
    BEFORE its in-flight work completes -- /readyz flips at drain START and
    the pool's drain watch pulls it from new-primary rotation while the
    in-flight predict is still running, then that predict finishes 200."""
    import requests

    from kubernetes_deep_learning_tpu.serving import protocol

    spec, server = _make_stub_server(
        "drain-order", tmp_path, device_ms=700.0
    )
    base = f"http://127.0.0.1:{server.port}"
    pool = UpstreamPool(
        [f"127.0.0.1:{server.port}"], failover=True, probe_interval_s=0.05
    )
    replica = pool.replicas[0]
    result: dict = {}

    def slow_predict():
        result["resp"] = requests.post(
            f"{base}/v1/models/{spec.name}:predict",
            data=protocol.encode_predict_request(IMG),
            headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
            timeout=30.0,
        )

    t = threading.Thread(target=slow_predict)
    try:
        t.start()
        time.sleep(0.15)  # the predict is on the device (700ms stub)
        server.begin_drain()  # the CLI's SIGTERM path
        # ORDERING: readiness flips while the request is still in flight...
        assert requests.get(f"{base}/readyz", timeout=5).status_code != 200
        assert t.is_alive(), "in-flight predict finished before the check"
        # ...the drain watch sees it and pulls the replica from rotation...
        pool.probe_once()
        assert replica.draining and not replica.routable
        assert pool.choose() is None  # no new primaries into a drain
        # ...and the in-flight request still completes successfully.
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert result["resp"].status_code == 200
        # Liveness stays 200 through the drain: k8s must not kill a
        # draining pod early.
        assert requests.get(f"{base}/healthz", timeout=5).status_code == 200
    finally:
        t.join(timeout=10.0)
        pool.close()
        server.shutdown()


def test_leave_under_load_drops_nothing(tmp_path):
    """A replica removed from membership mid-request: the in-flight work
    dispatched to it completes 200 (nothing cancelled), new picks go to
    the survivor only, and the leaver's accounting is retired."""
    spec, leaver = _make_stub_server(
        "leave-load", tmp_path, subdir="a", device_ms=500.0
    )
    _, survivor = _make_stub_server("leave-load", tmp_path, subdir="b")
    gw = Gateway(
        serving_host=f"127.0.0.1:{leaver.port},127.0.0.1:{survivor.port}",
        model=spec.name, port=0, bind=False, probe_interval_s=0,
    )
    result: dict = {}
    try:
        gw.spec
        gw.pool._rr = 0  # the in-flight request lands on the leaver

        def inflight():
            result["out"] = gw._predict_batch(IMG)

        t = threading.Thread(target=inflight)
        t.start()
        time.sleep(0.1)  # dispatched to the leaver (500ms stub)
        delta = gw.pool.set_membership([f"127.0.0.1:{survivor.port}"])
        assert delta["left"] == [f"127.0.0.1:{leaver.port}"]
        # New picks see only the survivor...
        only = gw.pool.replicas
        assert [r.host for r in only] == [f"127.0.0.1:{survivor.port}"]
        logits, _ = gw._predict_batch(IMG)
        assert np.asarray(logits).shape == (1, 3)
        # ...while the request already in flight on the leaver completes.
        t.join(timeout=10.0)
        assert not t.is_alive()
        logits, labels = result["out"]
        assert list(labels) == ["a", "b", "c"]
        text = gw.registry.render()
        assert _metric(text, "kdlt_pool_leaves_total") == 1.0
        assert f'replica="127.0.0.1:{leaver.port}"' not in text
    finally:
        gw.shutdown()
        leaver.shutdown()
        survivor.shutdown()


def test_gateway_debug_pool_reports_membership_and_picks(tmp_path):
    import json
    import urllib.request

    spec, server = _make_stub_server("dbg-pool", tmp_path)
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name,
        port=0, probe_interval_s=0,
    )
    try:
        gw.start()
        gw.spec
        gw._predict_batch(IMG)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{gw.port}/debug/pool", timeout=5
        ) as r:
            payload = json.loads(r.read())
        assert payload["members"] == 1
        assert payload["failover"] is True
        row = payload["replicas"][0]
        assert row["host"] == f"127.0.0.1:{server.port}"
        assert row["healthy"] is True and row["picks"] >= 1
        assert row["ewma_ms"] is None or row["ewma_ms"] > 0
        # The kdlt-client --stats rendering consumes exactly this payload.
        from kubernetes_deep_learning_tpu.serving.client import render_pool

        text = render_pool(payload)
        assert f"127.0.0.1:{server.port}" in text
        assert "up" in text and "picks" in text
    finally:
        gw.shutdown()
        server.shutdown()


def test_drain_watch_undrains_on_readyz_recovery(status_server):
    # A rollout aborted: /readyz flips 503 then back to 200 -- the replica
    # must re-enter rotation without a health (healthz) rejoin cycle.
    pool = UpstreamPool(
        [status_server.host], failover=True, probe_interval_s=0.05
    )
    r = pool.replicas[0]
    status_server.codes["/readyz"] = 503
    pool.probe_once()
    assert r.draining and not r.routable
    status_server.codes["/readyz"] = 200
    pool.probe_once()
    assert not r.draining and r.routable


def test_dead_while_draining_demotes_to_unhealthy(status_server):
    pool = UpstreamPool(
        [status_server.host], failover=True, probe_interval_s=0.05
    )
    r = pool.replicas[0]
    status_server.codes["/readyz"] = 503
    pool.probe_once()
    assert r.draining
    # The draining process dies: recovery is handed to the /healthz path
    # (draining is a live-process state; a dead one is just unhealthy).
    status_server.close()
    pool.probe_once()
    assert not r.draining and not r.healthy
