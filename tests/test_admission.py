"""Unit tests for the admission-control primitives (serving.admission).

Deadline parsing/clamping, the AIMD limiter's bounds and adaptation, the
circuit breaker's closed/open/half-open machine (fake clock, no sleeps),
and the controller's admit/shed/drain bookkeeping -- all device-free.
"""

from __future__ import annotations

import threading
import time

import pytest

from kubernetes_deep_learning_tpu.serving.admission import (
    AdaptiveLimiter,
    AdmissionController,
    CircuitBreaker,
    Deadline,
    Shed,
)
from kubernetes_deep_learning_tpu.serving.admission import breaker as breaker_mod
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib


# --- Deadline --------------------------------------------------------------


def test_deadline_header_parse_default_and_garbage(monkeypatch):
    monkeypatch.delenv("KDLT_ADMISSION_DEFAULT_DEADLINE_MS", raising=False)
    for raw in (None, "", "  ", "not-a-number"):
        d = Deadline.from_header(raw)
        assert d.budget_s == pytest.approx(20.0)  # the reference's 20 s
        assert not d.expired
    monkeypatch.setenv("KDLT_ADMISSION_DEFAULT_DEADLINE_MS", "5000")
    assert Deadline.from_header(None).budget_s == pytest.approx(5.0)


def test_deadline_header_clamp_and_exhaustion():
    # Oversized budgets are capped; non-positive ones arrive pre-exhausted.
    d = Deadline.from_header("999999999")
    assert d.budget_s <= 300.0
    for raw in ("0", "-50"):
        d = Deadline.from_header(raw)
        assert d.expired
    d = Deadline.from_header("250")
    assert 0.2 < d.remaining_s() <= 0.25
    assert float(d.header_value()) <= 250.0


def test_deadline_header_rejects_non_finite(monkeypatch):
    # float("nan") parses but slides through the min()/max() cap unchanged:
    # a never-expiring deadline that would defeat MAX_DEADLINE_MS and
    # re-propagate as "nan" downstream.  Non-finite -> the default budget.
    monkeypatch.delenv("KDLT_ADMISSION_DEFAULT_DEADLINE_MS", raising=False)
    for raw in ("nan", "NaN", "inf", "-inf"):
        d = Deadline.from_header(raw)
        assert d.budget_s == pytest.approx(20.0), raw
        assert float(d.header_value()) <= 20_000.0


def test_deadline_clamp_shrinks_timeouts():
    d = Deadline(0.1)
    assert d.clamp(20.0) <= 0.1
    assert Deadline(50.0).clamp(20.0) == 20.0
    # An expired deadline clamps to the floor, never to a non-positive
    # socket timeout (which would mean "wait forever").
    assert Deadline(-1.0).clamp(20.0, floor_s=0.05) == 0.05


# --- AdaptiveLimiter -------------------------------------------------------


def test_limiter_concurrency_bound_and_queue_full():
    lim = AdaptiveLimiter(min_limit=1, max_limit=2, initial=2, queue_cap=1,
                          max_queue_wait_s=0.05)
    assert lim.acquire() == 0.0
    assert lim.acquire() == 0.0
    # Third request queues; fourth overflows the 1-waiter cap immediately.
    t = threading.Thread(target=lambda: pytest.raises(Shed, lim.acquire))
    t.start()
    time.sleep(0.01)
    with pytest.raises(Shed) as exc:
        lim.acquire()
    assert exc.value.reason == "queue_full"
    assert exc.value.retry_after_s > 0
    t.join()


def test_limiter_queue_timeout_is_budget_fraction_bounded():
    lim = AdaptiveLimiter(min_limit=1, max_limit=1, initial=1, queue_cap=8)
    lim.acquire()
    t0 = time.monotonic()
    with pytest.raises(Shed) as exc:
        lim.acquire(budget_s=0.2)  # bounded at fraction 0.25 -> 50ms
    waited = time.monotonic() - t0
    assert exc.value.reason == "queue_timeout"
    assert waited < 0.15  # far less than the 200ms budget


def test_limiter_aimd_decrease_and_hold_and_increase():
    lim = AdaptiveLimiter(min_limit=1, max_limit=64, initial=8, cooldown_s=0.0)
    lim.acquire()
    lim.release(overloaded=True)  # multiplicative decrease
    assert lim.limit == pytest.approx(8 * 0.9)
    before = lim.limit
    lim.acquire()
    lim.release(headroom=False)  # hold band: neither grow nor shrink
    assert lim.limit == before
    lim.acquire()
    lim.release()  # clean + headroom: additive increase
    assert lim.limit == pytest.approx(before + 1.0 / before)
    # The floor holds under repeated congestion.
    for _ in range(100):
        lim.acquire()
        lim.release(overloaded=True)
    assert lim.limit == 1.0


def test_limiter_reconciles_inverted_bounds(monkeypatch):
    # min_limit above the (env-default 64) ceiling -- the model server's
    # 2x-max-bucket floor with default buckets is 256 -- must not invert
    # the AIMD bounds: release() would clamp decreases UP to min_limit,
    # RAISING admitted concurrency on congestion.
    monkeypatch.delenv("KDLT_ADMISSION_MAX_CONCURRENCY", raising=False)
    lim = AdaptiveLimiter(min_limit=256.0)
    assert lim.min_limit <= lim.max_limit
    assert lim.limit >= 256.0
    lim.acquire()
    before = lim.limit
    lim.release(overloaded=True)
    assert lim.limit <= before  # congestion never raises the limit


def test_limiter_timeout_renotifies_next_waiter():
    # release() issues a single notify; a woken waiter that is already past
    # its give-up time sheds -- it must pass the wakeup on, or the freed
    # slot idles while the remaining waiters sleep out their full bound.
    lim = AdaptiveLimiter(min_limit=1, max_limit=1, initial=1, queue_cap=8,
                          max_queue_wait_s=5.0)
    lim.acquire()
    results: list[str] = []

    def short():
        try:
            lim.acquire(budget_s=0.12)  # 30 ms wait bound
        except Shed:
            results.append("shed")
        else:
            lim.release()

    def long_wait():
        lim.acquire()  # 5 s bound: plenty once the wakeup is handed on
        results.append("acquired")

    ta = threading.Thread(target=short)
    ta.start()
    time.sleep(0.01)
    tb = threading.Thread(target=long_wait)
    tb.start()
    time.sleep(0.02)  # land the release around the short waiter's give-up
    lim.release()
    ta.join(timeout=5)
    tb.join(timeout=2)
    assert "acquired" in results, results


def test_limiter_release_wakes_waiter():
    lim = AdaptiveLimiter(min_limit=1, max_limit=1, initial=1, queue_cap=4,
                          max_queue_wait_s=5.0)
    lim.acquire()
    waited = []

    def waiter():
        waited.append(lim.acquire())

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    lim.release()
    t.join(timeout=5)
    assert waited and 0.0 < waited[0] < 5.0


# --- CircuitBreaker --------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_full_transition_cycle():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, reset_timeout_s=2.0,
                       half_open_probes=1, clock=clock)
    assert b.state == breaker_mod.CLOSED
    # Non-consecutive failures never trip.
    b.record_failure()
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state == breaker_mod.CLOSED
    # Three consecutive -> OPEN; everything refused with a cool-down hint.
    for _ in range(3):
        b.record_failure()
    assert b.state == breaker_mod.OPEN
    assert not b.allow()
    assert 0 < b.retry_after_s() <= 2.0
    # Cool-down elapsed -> HALF_OPEN: exactly one probe passes.
    clock.t = 2.5
    assert b.allow()
    assert b.state == breaker_mod.HALF_OPEN
    assert not b.allow()  # probe slot consumed; others shed
    # Probe success closes; traffic flows again.
    b.record_success()
    assert b.state == breaker_mod.CLOSED
    assert b.allow()


def test_breaker_probe_failure_reopens():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0,
                       half_open_probes=1, clock=clock)
    b.record_failure()
    assert b.state == breaker_mod.OPEN
    clock.t = 1.5
    assert b.allow()
    b.record_failure()  # the probe failed: straight back to OPEN
    assert b.state == breaker_mod.OPEN
    assert not b.allow()
    assert b.retry_after_s() == pytest.approx(1.0)


# --- AdmissionController ---------------------------------------------------


def test_controller_admits_and_tracks_inflight():
    reg = metrics_lib.Registry()
    ctl = AdmissionController(reg, tier="test", enabled=True)
    t1 = ctl.admit(Deadline(5.0))
    t2 = ctl.admit(Deadline(5.0))
    assert ctl.inflight == 2
    t1.release()
    t2.release()
    t2.release()  # idempotent
    assert ctl.inflight == 0
    assert ctl.wait_idle(timeout_s=0.1)
    rendered = reg.render()
    assert 'kdlt_admission_requests_total{tier="test"} 2' in rendered
    assert 'kdlt_admission_admitted_total{tier="test"} 2' in rendered


def test_controller_rejects_exhausted_deadline():
    reg = metrics_lib.Registry()
    ctl = AdmissionController(reg, tier="test", enabled=True)
    with pytest.raises(Shed) as exc:
        ctl.admit(Deadline(-0.01))
    assert exc.value.reason == "deadline_exhausted"
    assert exc.value.http_status == 504
    assert (
        'kdlt_admission_shed_total{tier="test",shed_reason="deadline_exhausted"} 1'
        in reg.render()
    )


def test_controller_disabled_tracks_but_never_sheds():
    reg = metrics_lib.Registry()
    ctl = AdmissionController(reg, tier="test", enabled=False)
    # Exhausted deadline, absurd concurrency: all admitted when disabled.
    tickets = [ctl.admit(Deadline(-1.0)) for _ in range(300)]
    assert ctl.inflight == 300
    for t in tickets:
        t.release()
    assert ctl.inflight == 0


def test_controller_drain_sheds_and_waits_for_inflight():
    reg = metrics_lib.Registry()
    ctl = AdmissionController(reg, tier="test", enabled=True)
    ticket = ctl.admit(Deadline(5.0))
    ctl.begin_drain()
    assert ctl.draining
    with pytest.raises(Shed) as exc:
        ctl.admit(Deadline(5.0))
    assert exc.value.reason == "draining"
    assert exc.value.retry_after_s is not None
    assert not ctl.wait_idle(timeout_s=0.05)  # still one in flight
    threading.Timer(0.05, ticket.release).start()
    assert ctl.wait_idle(timeout_s=5.0)
    assert 'kdlt_admission_draining{tier="test"} 1.0' in reg.render()


def test_admission_env_gate(monkeypatch):
    from kubernetes_deep_learning_tpu.serving.admission import admission_enabled

    monkeypatch.delenv("KDLT_ADMISSION", raising=False)
    assert admission_enabled() is True
    for off in ("0", "false", "off", "no"):
        monkeypatch.setenv("KDLT_ADMISSION", off)
        assert admission_enabled() is False
    monkeypatch.setenv("KDLT_ADMISSION", "1")
    assert admission_enabled() is True
    # Explicit argument always wins over the environment.
    monkeypatch.setenv("KDLT_ADMISSION", "0")
    assert admission_enabled(True) is True
