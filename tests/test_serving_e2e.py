"""End-to-end serving test: gateway -> model server -> engine -> response.

The reference's only test is a live smoke test against a deployed cluster
(reference test.py:1-16).  Here the same request path runs in-process on the
CPU backend: a real model server and a real gateway on ephemeral ports, a
local HTTP server standing in for the image host (no egress in CI), and the
reference's exact request/response schema asserted end to end.
"""

from __future__ import annotations

import io
import json
import threading
from functools import partial
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import export_model
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.serving.client import predict_images, predict_url
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """Exported tiny model + model server + gateway + image host, all live."""
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

    spec = register_spec(
        ModelSpec(
            name="e2e-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("dress", "hat", "pants", "shirt"),
            preprocessing="tf",
            resize_filter="nearest",
        )
    )
    root = tmp_path_factory.mktemp("models")
    variables = init_variables(spec, seed=5)
    export_model(spec, variables, str(root), dtype=np.float32)

    server = ModelServer(str(root), port=0, buckets=(1, 2, 4), max_delay_ms=1.0)
    server.warmup()
    server.start()

    gateway = Gateway(serving_host=f"localhost:{server.port}", model=spec.name, port=0)
    gateway.start()

    # Local image host: serves a generated PNG (reference's bit.ly stand-in).
    img_dir = tmp_path_factory.mktemp("images")
    rng = np.random.default_rng(0)
    pixels = rng.integers(0, 256, size=(120, 80, 3), dtype=np.uint8)
    from PIL import Image

    Image.fromarray(pixels).save(img_dir / "pants.png")
    img_httpd = HTTPServer(
        ("127.0.0.1", 0), partial(SimpleHTTPRequestHandler, directory=str(img_dir))
    )
    threading.Thread(target=img_httpd.serve_forever, daemon=True).start()
    image_url = f"http://127.0.0.1:{img_httpd.server_address[1]}/pants.png"

    yield spec, server, gateway, image_url, pixels, variables

    gateway.shutdown()
    server.shutdown()
    img_httpd.shutdown()


def test_gateway_predict_schema(stack):
    spec, _, gateway, image_url, _, _ = stack
    scores = predict_url(f"http://localhost:{gateway.port}", image_url)
    # Reference response schema: {label: float} for every class
    # (reference model_server.py:46-49,66).
    assert set(scores) == set(spec.labels)
    assert all(isinstance(v, float) for v in scores.values())


def test_gateway_matches_direct_forward(stack):
    import jax

    from kubernetes_deep_learning_tpu.models import build_forward
    from kubernetes_deep_learning_tpu.ops import preprocess

    spec, _, gateway, image_url, pixels, variables = stack
    scores = predict_url(f"http://localhost:{gateway.port}", image_url)

    expected_img = preprocess.resize_uint8(pixels, spec.input_shape[:2], "nearest")
    fwd = jax.jit(build_forward(spec, dtype=None))
    want = np.asarray(fwd(variables, expected_img[None]))[0]
    got = np.asarray([scores[l] for l in spec.labels], np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_model_server_batch_predict(stack):
    spec, server, _, _, _, variables = stack
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, size=(3, 96, 96, 3), dtype=np.uint8)
    logits, labels = predict_images(
        f"http://localhost:{server.port}", spec.name, imgs
    )
    assert logits.shape == (3, 4)
    assert labels == list(spec.labels)


def test_model_server_json_fallback(stack):
    import requests

    spec, server, _, _, _, _ = stack
    img = np.zeros((96, 96, 3), np.uint8)
    r = requests.post(
        f"http://localhost:{server.port}/v1/models/{spec.name}:predict",
        json={"instances": [img.tolist()]},
        timeout=30,
    )
    assert r.status_code == 200
    preds = r.json()["predictions"]
    assert len(preds) == 1 and set(preds[0]) == set(spec.labels)


def test_health_ready_metrics_endpoints(stack):
    import requests

    spec, server, gateway, image_url, _, _ = stack
    base_s = f"http://localhost:{server.port}"
    base_g = f"http://localhost:{gateway.port}"
    assert requests.get(f"{base_s}/healthz", timeout=5).status_code == 200
    assert requests.get(f"{base_s}/readyz", timeout=5).status_code == 200
    assert "kdlt_engine_images_total" in requests.get(f"{base_s}/metrics", timeout=5).text
    assert requests.get(f"{base_g}/healthz", timeout=5).status_code == 200
    assert requests.get(f"{base_g}/readyz", timeout=5).status_code == 200
    assert "kdlt_gateway_requests_total" in requests.get(f"{base_g}/metrics", timeout=5).text

    models = requests.get(f"{base_s}/v1/models", timeout=5).json()
    assert models[spec.name]["ready"] is True
    spec_json = requests.get(f"{base_s}/v1/models/{spec.name}", timeout=5).json()
    assert spec_json["name"] == spec.name


def test_error_paths(stack):
    import requests

    spec, server, gateway, _, _, _ = stack
    # gateway: bad URL in body
    r = requests.post(
        f"http://localhost:{gateway.port}/predict",
        json={"url": "http://127.0.0.1:1/nope.png"},
        timeout=30,
    )
    assert r.status_code == 400 and "error" in r.json()
    # gateway: missing url key
    r = requests.post(f"http://localhost:{gateway.port}/predict", json={}, timeout=30)
    assert r.status_code == 400
    # model server: unknown model
    r = requests.post(
        f"http://localhost:{server.port}/v1/models/nope:predict", data=b"{}", timeout=30
    )
    assert r.status_code == 404
    # model server: wrong input shape
    r = requests.post(
        f"http://localhost:{server.port}/v1/models/{spec.name}:predict",
        json={"instances": [np.zeros((4, 4, 3), np.uint8).tolist()]},
        timeout=30,
    )
    assert r.status_code == 400 and "shape" in r.json()["error"]


def test_concurrent_gateway_requests(stack):
    spec, _, gateway, image_url, _, _ = stack
    results = []
    errors = []

    def hit():
        try:
            results.append(predict_url(f"http://localhost:{gateway.port}", image_url))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=hit) for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors and len(results) == 12
    # Concurrent identical requests may land in different batch buckets;
    # each bucket is a separately compiled program, so allow fusion-level
    # rounding drift (same tolerance story as test_xception.py).
    first = results[0]
    for r in results[1:]:
        for label in first:
            assert abs(r[label] - first[label]) < 5e-3, (label, r, first)


def test_gateway_batch_urls(stack):
    # Beyond-reference extension: {"urls": [...]} -> {"predictions": [...]},
    # order preserved, one bad URL failing only its own entry.
    import requests

    spec, _, gateway, image_url, _, _ = stack
    bad_url = image_url.replace("pants.png", "missing.png")
    r = requests.post(
        f"http://localhost:{gateway.port}/predict",
        json={"urls": [image_url, bad_url, image_url]},
        timeout=30,
    )
    assert r.status_code == 200, r.text
    preds = r.json()["predictions"]
    assert len(preds) == 3
    assert set(preds[0]) == set(spec.labels)
    assert "error" in preds[1]
    assert preds[2] == preds[0]


def test_gateway_retries_transient_503(stack, monkeypatch):
    # First upstream response is the model tier's overload signal; the
    # gateway must retry once and succeed rather than surface the 503.
    spec, _, gateway, image_url, _, _ = stack
    real_post = gateway._session().post
    calls = {"n": 0}

    class Fake503:
        status_code = 503
        text = "overloaded"
        headers: dict = {}

    def flaky_post(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            return Fake503()
        return real_post(*args, **kwargs)

    monkeypatch.setattr(gateway._session(), "post", flaky_post)
    scores = gateway.apply_model(image_url)
    assert set(scores) == set(spec.labels)
    assert calls["n"] == 2


def test_oversized_batch_is_chunked_not_rejected(stack):
    # The e2e stack's buckets stop at 4; a 10-image request must be served
    # in bucket-sized chunks, not bounced with "exceeds max bucket".
    spec, server, _, _, pixels, _ = stack
    from kubernetes_deep_learning_tpu.ops.preprocess import resize_uint8

    img = resize_uint8(pixels, spec.input_shape[:2], filter=spec.resize_filter)
    batch = np.stack([img] * 10)
    logits, labels = predict_images(
        f"http://localhost:{server.port}", spec.name, batch
    )
    assert logits.shape == (10, spec.num_classes)
    # Identical inputs, identical rows (chunk boundaries must not matter).
    np.testing.assert_allclose(logits, np.tile(logits[:1], (10, 1)), atol=1e-5)


def test_gateway_batch_larger_than_tier_buckets(stack):
    import requests

    spec, _, gateway, image_url, _, _ = stack
    r = requests.post(
        f"http://localhost:{gateway.port}/predict",
        json={"urls": [image_url] * 6},  # > the tier's max bucket of 4
        timeout=60,
    )
    assert r.status_code == 200, r.text
    preds = r.json()["predictions"]
    assert len(preds) == 6 and all(set(p) == set(spec.labels) for p in preds)


def test_gateway_batch_url_cap(stack):
    import requests

    from kubernetes_deep_learning_tpu.serving import gateway as gw_mod

    _, _, gateway, image_url, _, _ = stack
    r = requests.post(
        f"http://localhost:{gateway.port}/predict",
        json={"urls": [image_url] * (gw_mod.MAX_URLS_PER_REQUEST + 1)},
        timeout=60,
    )
    assert r.status_code == 400
    assert "limit" in r.json()["error"]


def test_request_byte_limit_precedes_read(stack):
    # The server must reject an oversized Content-Length BEFORE reading or
    # decoding the body (the cap is a memory bound, not a shape check) --
    # it answers 400 while the client has sent no body bytes at all.
    # Raw http.client: requests would overwrite a forged Content-Length.
    import http.client

    spec, server, _, _, _, _ = stack
    conn = http.client.HTTPConnection("localhost", server.port, timeout=30)
    try:
        conn.putrequest("POST", f"/v1/models/{spec.name}:predict")
        conn.putheader("Content-Type", "application/json")
        conn.putheader("Content-Length", str(64 * 1024**3))
        conn.endheaders()
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 400
        assert "limit" in body["error"]
    finally:
        conn.close()


def test_request_id_traced_across_tiers(stack, capsys):
    """One X-Request-Id travels client -> gateway -> model server and back:
    echoed in both tiers' response headers and stamped on both tiers' log
    lines (VERDICT r1 item 10; the reference has no tracing at all)."""
    import requests

    from kubernetes_deep_learning_tpu.serving.tracing import REQUEST_ID_HEADER

    _, server, gateway, image_url, _, _ = stack
    rid = "e2e-trace-abc123"
    gateway.request_log = True
    server.request_log = True
    try:
        # A fresh URL identity: a response-cache hit would (correctly)
        # never reach the model tier, and this test asserts the FULL
        # cross-tier propagation path.
        r = requests.post(
            f"http://localhost:{gateway.port}/predict",
            json={"url": image_url + "?trace-propagation=1"},
            headers={REQUEST_ID_HEADER: rid},
            timeout=60,
        )
    finally:
        gateway.request_log = False
        server.request_log = False
    assert r.status_code == 200
    assert r.headers[REQUEST_ID_HEADER] == rid

    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if f"[rid={rid}]" in l]
    assert any("gateway predict" in l and "status=200" in l for l in lines), out
    assert any("model-server predict" in l and "status=200" in l for l in lines), out


def test_request_id_minted_and_sanitized(stack):
    """Without a client id the gateway mints one; a hostile id is stripped
    of header/log metacharacters before being echoed anywhere."""
    import requests

    from kubernetes_deep_learning_tpu.serving.tracing import REQUEST_ID_HEADER

    _, _, gateway, image_url, _, _ = stack
    base = f"http://localhost:{gateway.port}"
    r = requests.post(base + "/predict", json={"url": image_url}, timeout=60)
    assert len(r.headers[REQUEST_ID_HEADER]) == 16

    evil = "abc\rX-Injected: 1\nDEF[]"
    r = requests.post(
        base + "/predict",
        json={"url": image_url},
        headers={REQUEST_ID_HEADER: evil.replace("\r", "").replace("\n", "")},
        timeout=60,
    )
    assert r.headers[REQUEST_ID_HEADER] == "abcX-Injected1DEF"
    assert "X-Injected" not in r.headers


def test_second_model_hot_added_and_served(stack):
    """A NEW model dropped under the model root is discovered by the same
    scan the version watcher and the gRPC reload RPC share, warmed before
    the swap, and served ALONGSIDE the original -- the multi-model surface
    of the TF-Serving convention, which the reference's one-model-per-image
    flow never exercises (reference tf-serving.dockerfile:5)."""
    import urllib.request

    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.serving import protocol

    spec, server, gateway, image_url, pixels, variables = stack
    vit = register_spec(
        ModelSpec(
            name="e2e-vit",
            family="vit-tiny",
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
        )
    )
    export_model(vit, init_variables(vit, seed=1), server.model_root)
    updated = server.poll_versions()
    assert any("e2e-vit" in u for u in updated), updated
    assert "e2e-vit" in server.models and server.ready

    img = np.zeros((2, 32, 32, 3), np.uint8)
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/v1/models/e2e-vit:predict",
        data=protocol.encode_predict_request(img),
        headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
    )
    resp = urllib.request.urlopen(req, timeout=60)
    logits, labels = protocol.decode_predict_response(
        resp.read(), resp.headers["Content-Type"]
    )
    assert logits.shape == (2, 3) and list(labels) == ["a", "b", "c"]
    assert np.all(np.isfinite(logits))

    # The original model keeps serving from the same process.
    out_logits, out_labels = predict_images(
        f"http://localhost:{server.port}", spec.name,
        np.zeros((1, 96, 96, 3), np.uint8),
    )
    assert out_logits.shape == (1, spec.num_classes)
    assert out_labels == list(spec.labels)
