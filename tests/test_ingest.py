"""Raw-bytes ingest wire: decode at the model tier (ISSUE 20, GUIDE 10q).

Four layers of coverage: the protocol surface (content type, capability
negotiation tokens, format sniffing, the bytes-wire msgpack frame and its
validation errors), the model tier's vectorized decode stage
(ops/preprocess.BatchDecoder parity with the gateway's per-image path,
per-index error naming), the decoded-uint8 cache tier
(serving/cache.DecodedCache content addressing, LRU budget, read-only
entries), and real HTTP stacks e2e: bytes wire end to end with
bit-identical scores across wires, the mixed-version negotiation
fallback, the per-request rejected fallback, and corrupt bytes answering
400 -- never 500.
"""

from __future__ import annotations

import io
import json
import os
import threading
from functools import partial
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.ops import preprocess
from kubernetes_deep_learning_tpu.serving import cache as cache_lib
from kubernetes_deep_learning_tpu.serving import protocol


def _jpeg_bytes(seed: int = 0, size: int = 48) -> bytes:
    from PIL import Image

    rng = np.random.default_rng(seed)
    buf = io.BytesIO()
    Image.fromarray(
        rng.integers(0, 256, size=(size, size, 3), dtype=np.uint8)
    ).save(buf, format="JPEG", quality=90)
    return buf.getvalue()


def _png_bytes(seed: int = 0, size: int = 48) -> bytes:
    from PIL import Image

    rng = np.random.default_rng(seed)
    buf = io.BytesIO()
    Image.fromarray(
        rng.integers(0, 256, size=(size, size, 3), dtype=np.uint8)
    ).save(buf, format="PNG")
    return buf.getvalue()


# --- protocol surface --------------------------------------------------------


def test_sniff_image_format_recognizes_exactly_the_wire_formats():
    assert protocol.sniff_image_format(_jpeg_bytes()) == "jpeg"
    assert protocol.sniff_image_format(_png_bytes()) == "png"
    assert protocol.sniff_image_format(b"") is None
    assert protocol.sniff_image_format(b"GIF89a...") is None
    assert protocol.sniff_image_format(b"{\"url\": \"json\"}") is None
    # Truncated magic is not a match.
    assert protocol.sniff_image_format(b"\xff\xd8") is None


def test_bytes_predict_request_round_trip():
    blobs = [_jpeg_bytes(0), _png_bytes(1), _jpeg_bytes(2)]
    body = protocol.encode_bytes_predict_request(blobs)
    assert protocol.decode_bytes_predict_request(body) == blobs


def test_bytes_predict_request_validation_errors():
    with pytest.raises(ValueError):
        protocol.decode_bytes_predict_request(b"not msgpack at all")
    with pytest.raises(ValueError):
        protocol.decode_bytes_predict_request(
            protocol.encode_bytes_predict_request([])
        )
    # Non-bytes entries are a malformed frame, not a decode error later.
    import msgpack

    with pytest.raises(ValueError):
        protocol.decode_bytes_predict_request(
            msgpack.packb({"images": ["a string"]})
        )
    with pytest.raises(ValueError):
        protocol.decode_bytes_predict_request(msgpack.packb({"nope": []}))
    # The per-request image cap is enforced at the frame boundary.
    body = protocol.encode_bytes_predict_request([b"x" * 8] * 3)
    with pytest.raises(ValueError):
        protocol.decode_bytes_predict_request(body, max_images=2)
    # An oversized blob is refused before any decode attempt.
    huge = b"\xff\xd8\xff" + b"x" * protocol.MAX_ENCODED_IMAGE_BYTES
    with pytest.raises(ValueError):
        protocol.decode_bytes_predict_request(
            protocol.encode_bytes_predict_request([huge])
        )


def test_ingest_enabled_env_gate(monkeypatch):
    monkeypatch.delenv(protocol.INGEST_ENV, raising=False)
    assert protocol.ingest_enabled() is True  # default posture: on
    for off in ("0", "false", "off", "no"):
        monkeypatch.setenv(protocol.INGEST_ENV, off)
        assert protocol.ingest_enabled() is False
    monkeypatch.setenv(protocol.INGEST_ENV, "1")
    assert protocol.ingest_enabled() is True
    # Explicit argument wins over the env (constructor kwargs beat posture).
    monkeypatch.setenv(protocol.INGEST_ENV, "0")
    assert protocol.ingest_enabled(True) is True
    monkeypatch.delenv(protocol.INGEST_ENV, raising=False)
    assert protocol.ingest_enabled(False) is False


def test_parse_ingest_caps_tolerates_unknown_and_absent():
    assert protocol.parse_ingest_caps(None) == ()
    assert protocol.parse_ingest_caps("") == ()
    assert protocol.parse_ingest_caps("bytes") == ("bytes",)
    # A future server advertising more: unknown tokens are DROPPED, so
    # an old gateway only ever sees capabilities it understands and the
    # handshake can never fail on vocabulary drift.
    caps = protocol.parse_ingest_caps(" bytes , future-cap ")
    assert caps == ("bytes",)
    assert protocol.parse_ingest_caps("future-only") == ()
    assert protocol.INGEST_BYTES_CAP in protocol.INGEST_CAPS


# --- the model tier's decode stage ------------------------------------------


def test_batch_decoder_matches_the_gateway_per_image_path():
    """The wires must be bit-identical: the model tier's pooled decode
    stage and the gateway's legacy per-image preprocess must produce the
    same uint8 pixels for the same bytes and params."""
    blobs = [_jpeg_bytes(s, size=40 + 8 * s) for s in range(5)]
    dec = preprocess.BatchDecoder(workers=3)
    try:
        for filt in ("bilinear", "nearest"):
            batch = dec.decode_batch(blobs, (32, 32), filter=filt)
            assert batch.shape == (5, 32, 32, 3) and batch.dtype == np.uint8
            for i, blob in enumerate(blobs):
                ref = preprocess.preprocess_bytes(blob, (32, 32), filter=filt)
                np.testing.assert_array_equal(batch[i], ref)
        # The single-image inline fast path agrees with the pooled path.
        one = dec.decode_batch(blobs[:1], (32, 32))
        np.testing.assert_array_equal(
            one[0], preprocess.preprocess_bytes(blobs[0], (32, 32))
        )
    finally:
        dec.close()


def test_batch_decoder_names_the_corrupt_index():
    dec = preprocess.BatchDecoder(workers=2)
    try:
        blobs = [_jpeg_bytes(0), b"\xff\xd8\xffcorrupt-not-a-jpeg", _jpeg_bytes(1)]
        with pytest.raises(ValueError, match="image 1"):
            dec.decode_batch(blobs, (32, 32))
        with pytest.raises(ValueError, match="empty"):
            dec.decode_batch([], (32, 32))
    finally:
        dec.close()


def test_resolve_decode_pool(monkeypatch):
    monkeypatch.delenv(preprocess.DECODE_POOL_ENV, raising=False)
    assert preprocess.resolve_decode_pool() == preprocess.DEFAULT_DECODE_POOL
    assert preprocess.resolve_decode_pool(3) == 3
    monkeypatch.setenv(preprocess.DECODE_POOL_ENV, "5")
    assert preprocess.resolve_decode_pool() == 5
    assert preprocess.resolve_decode_pool(2) == 2  # explicit beats env
    monkeypatch.setenv(preprocess.DECODE_POOL_ENV, "0")
    assert preprocess.resolve_decode_pool() >= 1  # never a dead pool


# --- the decoded-uint8 cache tier -------------------------------------------


def test_decoded_key_separates_content_and_params():
    p32 = cache_lib.decoded_params((32, 32, 3), "bilinear")
    p64 = cache_lib.decoded_params((64, 64, 3), "bilinear")
    pn = cache_lib.decoded_params((32, 32, 3), "nearest")
    blob = _jpeg_bytes(0)
    k = cache_lib.decoded_key(blob, p32)
    assert k == cache_lib.decoded_key(blob, p32)
    assert len(k) == 64  # sha256 hex
    # Same content at different params, or different content at the same
    # params, must never collide.
    assert k != cache_lib.decoded_key(blob, p64)
    assert k != cache_lib.decoded_key(blob, pn)
    assert k != cache_lib.decoded_key(_jpeg_bytes(1), p32)


def test_decoded_cache_hit_miss_lru_and_read_only():
    c = cache_lib.DecodedCache(max_mb=1.0)
    c.max_bytes = 3 * 32 * 32 * 3 - 1  # two entries fit, three cannot
    arrs = [
        np.full((32, 32, 3), i, dtype=np.uint8) for i in range(3)
    ]
    assert c.get("a") is None
    assert c.put("a", arrs[0]) is True
    assert c.put("b", arrs[1]) is True
    got = c.get("a")  # LRU touch: "b" is now the oldest
    np.testing.assert_array_equal(got, arrs[0])
    # Entries are immutable by contract; get() enforces it cheaply.
    with pytest.raises(ValueError):
        got[0, 0, 0] = 1
    assert c.put("c", arrs[2]) is True
    assert c.get("b") is None and c.get("a") is not None
    st = c.stats()
    assert st["evictions"] == 1 and st["entries"] == 2
    assert st["hits"] == 2 and st["resident_bytes"] <= c.max_bytes
    # An entry bigger than the whole budget is refused outright.
    assert c.put("huge", np.zeros((256, 256, 3), np.uint8)) is False


def test_decoded_cache_zero_budget_disables_the_tier():
    c = cache_lib.DecodedCache(max_mb=0.0)
    assert c.enabled is False
    assert c.put("k", np.zeros((4, 4, 3), np.uint8)) is False
    assert c.get("k") is None
    assert c.stats()["enabled"] is False


def test_decoded_cache_env_budget(monkeypatch):
    monkeypatch.setenv(cache_lib.DECODED_MB_ENV, "2")
    assert cache_lib.DecodedCache().max_bytes == 2 * 1024 * 1024
    monkeypatch.delenv(cache_lib.DECODED_MB_ENV, raising=False)
    assert cache_lib.DecodedCache().max_bytes == int(
        cache_lib.DEFAULT_DECODED_MB * 1024 * 1024
    )


# --- the fused device-resize staging knob -----------------------------------


def test_ingest_device_resize_parses_or_refuses(monkeypatch):
    from kubernetes_deep_learning_tpu.runtime.engine import (
        INGEST_DEVICE_RESIZE_ENV,
        ingest_device_resize,
    )

    monkeypatch.delenv(INGEST_DEVICE_RESIZE_ENV, raising=False)
    assert ingest_device_resize() is None  # off by default: host resize rules
    for off in ("", "0", "off", "false", "no"):
        monkeypatch.setenv(INGEST_DEVICE_RESIZE_ENV, off)
        assert ingest_device_resize() is None
    monkeypatch.setenv(INGEST_DEVICE_RESIZE_ENV, "512x384")
    assert ingest_device_resize() == (512, 384)
    assert ingest_device_resize("96x96") == (96, 96)  # explicit beats env
    for bad in ("512", "0x64", "-1x64", "axb"):
        with pytest.raises(ValueError):
            ingest_device_resize(bad)


# --- real HTTP stacks e2e ----------------------------------------------------


class _Quiet(SimpleHTTPRequestHandler):
    def log_message(self, fmt, *args):
        pass


def _image_server(tmp_path):
    from PIL import Image

    img_dir = tmp_path / "img"
    img_dir.mkdir(exist_ok=True)
    rng = np.random.default_rng(0)
    Image.fromarray(
        rng.integers(0, 256, size=(48, 48, 3), dtype=np.uint8)
    ).save(os.path.join(str(img_dir), "img.jpg"), quality=90)
    httpd = HTTPServer(
        ("127.0.0.1", 0), partial(_Quiet, directory=str(img_dir))
    )
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}/img.jpg"


def _stack(tmp_path, name: str, server_ingest: bool, gw_ingest: bool = True):
    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
    from kubernetes_deep_learning_tpu.serving.gateway import Gateway
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    spec = register_spec(
        ModelSpec(
            name=name,
            family="xception",  # never instantiated by StubEngine
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )
    root = tmp_path / f"models-{name}-{server_ingest}-{gw_ingest}"
    art.save_artifact(
        art.version_dir(str(root), spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        str(root), port=0, buckets=(1, 2), max_delay_ms=1.0, host="127.0.0.1",
        ingest=server_ingest,
        engine_factory=lambda a, **kw: StubEngine(a, **kw),
    )
    server.warmup()
    server.start()
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name,
        port=0, host="127.0.0.1", cache=False, ingest=gw_ingest,
    )
    gw.start()
    gw.spec  # resolve the contract (and the ingest caps) up front
    return spec, server, gw


def test_e2e_bytes_wire_end_to_end(tmp_path):
    """New gateway + new server: single and batch requests ride the bytes
    wire with zero fallbacks, the model tier's decoded cache memoizes
    repeated content, /debug/cache grows the decoded section, the legacy
    tensor wire still answers on the same server, and flipping the
    server's posture after negotiation triggers the per-request rejected
    fallback with an identical result."""
    import requests

    httpd, img_url = _image_server(tmp_path)
    spec, server, gw = _stack(tmp_path, "ingest-e2e", server_ingest=True)
    try:
        r1 = gw.apply_model(img_url)
        assert gw._m_ingest["bytes_requests"].value == 1
        assert set(r1) == {"a", "b", "c"}
        rb = gw.apply_model_batch([img_url, img_url])
        assert gw._m_ingest["bytes_requests"].value == 2
        assert rb == [r1, r1]
        assert all(
            c.value == 0 for c in gw._m_ingest["fallbacks"].values()
        ), "steady state must not fall back"
        # The model tier decoded every image; repeated content hit its
        # decoded cache (3 identical blobs so far).
        st = server._decoded_cache.stats()
        assert st["hits"] >= 1 and st["entries"] >= 1
        assert server._m_ingest["decoded_images"].value >= 1

        # The gateway's /debug/cache carries the decoded section even
        # with the response cache off.
        dbg = requests.get(
            f"http://127.0.0.1:{gw.port}/debug/cache", timeout=5
        ).json()
        assert dbg["decoded"]["enabled"] is True

        # The legacy tensor wire is still a first-class citizen on the
        # SAME server (old gateways keep working against new servers).
        from PIL import Image

        img = np.asarray(
            Image.open(io.BytesIO(requests.get(img_url, timeout=5).content))
            .convert("RGB").resize((32, 32), Image.BILINEAR),
            dtype=np.uint8,
        )
        rr = requests.post(
            f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict",
            data=protocol.encode_predict_request(img[None]),
            headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
            timeout=10,
        )
        assert rr.status_code == 200, rr.text

        # Rejected fallback: the gateway negotiated bytes, the server
        # flips its posture (a rollback race) -- the SAME request decodes
        # locally, resends on the tensor wire, and succeeds.
        server._ingest_enabled = False
        r3 = gw.apply_model(img_url)
        assert gw._m_ingest["fallbacks"]["rejected"].value == 1
        assert r3 == r1, "the fallback resend must score identically"
    finally:
        gw.shutdown()
        server.shutdown()
        httpd.shutdown()


def test_e2e_corrupt_bytes_answer_400_never_500(tmp_path):
    import requests

    spec, server, gw = _stack(tmp_path, "ingest-corrupt", server_ingest=True)
    try:
        url = f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict"
        for body in (
            protocol.encode_bytes_predict_request(
                [b"\xff\xd8\xffsniffable but undecodable"]
            ),
            protocol.encode_bytes_predict_request(
                [_jpeg_bytes(0), b"not an image at all"]
            ),
            b"not even msgpack",
        ):
            rr = requests.post(
                url, data=body,
                headers={"Content-Type": protocol.BYTES_CONTENT_TYPE},
                timeout=10,
            )
            assert rr.status_code == 400, (rr.status_code, rr.text)
        # A corrupt blob that SNIFFS as an image also fails the gateway's
        # local fallback decode and surfaces as the client's 400.
        detail = json.loads(rr.text) if rr.text.startswith("{") else {}
        assert detail is not None  # body shape is transport-defined
    finally:
        gw.shutdown()
        server.shutdown()


def test_e2e_negotiation_fallback_against_an_old_server(tmp_path):
    """Mixed versions: a bytes-capable gateway in front of a server that
    does not advertise the capability (KDLT_INGEST=0 stands in for an
    old build) must ride the tensor wire per-request -- and score
    bit-identically to a full bytes-wire stack."""
    httpd, img_url = _image_server(tmp_path)
    spec_new, server_new, gw_new = _stack(
        tmp_path, "ingest-new", server_ingest=True
    )
    spec_old, server_old, gw_old = _stack(
        tmp_path, "ingest-old", server_ingest=False
    )
    try:
        r_new = gw_new.apply_model(img_url)
        r_old = gw_old.apply_model(img_url)
        assert gw_old._m_ingest["bytes_requests"].value == 0
        assert gw_old._m_ingest["fallbacks"]["negotiation"].value >= 1
        assert gw_new._m_ingest["bytes_requests"].value == 1
        # Identical StubEngine + identical host preprocess on both tiers:
        # the wires must not perturb a single logit.
        assert r_new == r_old, "wires diverged on the same image"
        # Batch requests fall back the same way.
        rb = gw_old.apply_model_batch([img_url, img_url])
        assert rb == [r_old, r_old]
        assert gw_old._m_ingest["bytes_requests"].value == 0
    finally:
        gw_new.shutdown()
        server_new.shutdown()
        gw_old.shutdown()
        server_old.shutdown()
        httpd.shutdown()


def test_e2e_gateway_kill_switch_restores_the_legacy_posture(tmp_path):
    """KDLT_INGEST=0 on the gateway alone: no bytes wire, no fallback
    counters (the legacy path is not a fallback, it is the configured
    posture), correct scores."""
    httpd, img_url = _image_server(tmp_path)
    spec, server, gw = _stack(
        tmp_path, "ingest-off-gw", server_ingest=True, gw_ingest=False
    )
    try:
        r = gw.apply_model(img_url)
        assert set(r) == {"a", "b", "c"}
        assert gw._m_ingest["bytes_requests"].value == 0
        assert all(c.value == 0 for c in gw._m_ingest["fallbacks"].values())
    finally:
        gw.shutdown()
        server.shutdown()
        httpd.shutdown()
