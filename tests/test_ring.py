"""Ring attention over the 8-device CPU mesh vs single-device reference.

Exactness is the point: ring attention is a communication schedule, not an
approximation, so results must match full attention to float tolerance
even though KV shards arrive via 7 ppermute hops.
"""

from __future__ import annotations

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.ops.attention import mha_reference
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.ring import ring_attention


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8, model_parallel=1)


def _rand_qkv(rng, b=1, h=2, s=128, d=32):
    shape = (b, h, s, d)
    return tuple(rng.standard_normal(shape).astype(np.float32) for _ in range(3))


@pytest.mark.parametrize("use_flash", [False, True, None])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(mesh8, causal, use_flash):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng)
    got = ring_attention(q, k, v, mesh8, causal=causal, use_flash=use_flash)
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-5, rtol=3e-5)


def test_ring_falls_back_when_shard_has_no_tiling():
    """s_local = 9 has no MXU block size: auto mode must use the einsum
    path instead of failing, and explicit use_flash=True must raise."""
    mesh2 = make_mesh(2, model_parallel=1)
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, s=18)
    got = ring_attention(q, k, v, mesh2, causal=True)
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-5, rtol=3e-5)
    with pytest.raises(ValueError, match="no MXU tiling"):
        ring_attention(q, k, v, mesh2, use_flash=True)


def test_ring_output_keeps_sequence_sharding(mesh8):
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng)
    out = ring_attention(q, k, v, mesh8)
    # S stays sharded over the data axis: 8 shards, one per device.
    assert len(out.sharding.device_set) == 8
    spec = out.sharding.spec
    assert spec[2] == "data"


def test_ring_rejects_indivisible_sequence(mesh8):
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, s=100)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh8)


def test_ring_on_subset_mesh():
    mesh2 = make_mesh(2, model_parallel=1)
    rng = np.random.default_rng(3)
    q, k, v = _rand_qkv(rng, s=64)
    got = ring_attention(q, k, v, mesh2, causal=True)
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-5, rtol=3e-5)


def test_ring_trainable_matches_autodiff_reference(mesh8):
    """Gradients through the trainable ring == autodiff of the full einsum
    reference, for both causal and bidirectional attention (the backward
    ring: dq local, dk/dv rotated home; ROADMAP r1 closed)."""
    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.ops.attention import mha_reference
    from kubernetes_deep_learning_tpu.parallel.ring import (
        build_ring_attention_trainable,
    )

    rng = np.random.default_rng(11)
    b, h, s, d = 1, 2, 64, 16
    q = jnp.asarray(rng.normal(0, 1, (b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (b, h, s, d)), jnp.float32)

    for causal in (False, True):
        ring_fn = build_ring_attention_trainable(mesh8, causal=causal)

        def loss_ring(q, k, v):
            return (ring_fn(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (mha_reference(q, k, v, causal=causal) ** 2).sum()

        out_ring = ring_fn(q, k, v)
        out_ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out_ring), np.asarray(out_ref), rtol=2e-3, atol=2e-3
        )
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, bb, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(bb), rtol=5e-3, atol=5e-3,
                err_msg=f"d{name} mismatch (causal={causal})",
            )
