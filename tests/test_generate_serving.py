"""The generative lane's transport half (serving/generate.py): request
handling, SSE framing, per-token SLO closure, and the /debug/slo decode
section.  The lane runs its real engine + scheduler (tiny model, CPU);
one module-scoped lane serves every test.  The full HTTP path --
model server ``:generate`` route, gateway ``/generate`` relay, chunked
streaming, kdlt-client -- is covered by the slow-marked end-to-end test
at the bottom.
"""

from __future__ import annotations

import json
import threading

import pytest

from kubernetes_deep_learning_tpu.serving import generate as generate_lib
from kubernetes_deep_learning_tpu.serving import protocol
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib


class _SloSpy:
    def __init__(self):
        self.calls = []

    def record(self, model, status, dt, deadline_exceeded=False):
        self.calls.append((model, status, deadline_exceeded))


@pytest.fixture(scope="module")
def slo_spy():
    return _SloSpy()


@pytest.fixture(scope="module")
def registry():
    return metrics_lib.Registry()


@pytest.fixture(scope="module")
def lane(slo_spy, registry):
    lane = generate_lib.GenerateLane(
        "gen-test", registry=registry, slo=slo_spy,
        engine_kwargs=dict(max_slots=2, page_size=8, max_pages_per_seq=4),
    )
    yield lane
    lane.close()


def test_decode_enabled_reads_the_env_switch(monkeypatch):
    monkeypatch.delenv("KDLT_DECODE", raising=False)
    assert generate_lib.decode_enabled(None) is False
    assert generate_lib.decode_enabled(True) is True
    assert generate_lib.decode_enabled(False) is False
    monkeypatch.setenv("KDLT_DECODE", "1")
    assert generate_lib.decode_enabled(None) is True
    # Explicit wins over env either way.
    assert generate_lib.decode_enabled(False) is False


def test_json_mode_answers_one_document(lane, slo_spy):
    slo_spy.calls.clear()
    status, body, ctype, extra = lane.handle_generate(
        json.dumps({"prompt": "hi", "max_new_tokens": 4,
                    "stream": False}).encode(),
        rid="json-1",
    )
    assert status == 200 and ctype == protocol.JSON_CONTENT_TYPE
    doc = json.loads(body)
    assert doc["tokens"] == len(doc["text"].encode("utf-8", "replace")) or \
        doc["tokens"] >= 1  # EOS may cut the text short of the budget
    assert doc["finish_reason"] in ("stop", "length")
    assert doc["ttft_ms"] >= 0
    # The lane recorded exactly one SLO outcome for the request.
    assert slo_spy.calls == [("gen-test", 200, False)]


def test_stream_mode_yields_sse_frames_with_terminal_done(lane):
    status, payload, ctype, extra = lane.handle_generate(
        json.dumps({"prompt": "hello", "max_new_tokens": 5}).encode(),
        rid="sse-1",
    )
    assert status == 200
    assert ctype == protocol.EVENT_STREAM_CONTENT_TYPE
    # Streams must never enter any cache along the way.
    assert extra["Cache-Control"] == "no-store"
    events = protocol.parse_sse_events(b"".join(payload))
    done = events[-1]
    assert done["done"] is True
    assert done["tokens"] == len(events) - 1
    # The done event's transcript equals the concatenated token texts.
    assert done["text"] == "".join(e["text"] for e in events[:-1])


def test_stream_and_json_agree_on_the_same_prompt(lane):
    _, payload, _, _ = lane.handle_generate(
        json.dumps({"prompt": "same prompt", "max_new_tokens": 6}).encode()
    )
    streamed = protocol.parse_sse_events(b"".join(payload))[-1]["text"]
    _, body, _, _ = lane.handle_generate(
        json.dumps({"prompt": "same prompt", "max_new_tokens": 6,
                    "stream": False}).encode()
    )
    assert json.loads(body)["text"] == streamed


def test_malformed_and_unfittable_bodies_are_400(lane, slo_spy):
    slo_spy.calls.clear()
    status, body, ctype, _ = lane.handle_generate(b"notjson")
    assert status == 400 and b"error" in body
    # Prompt + budget beyond the 32-token context: rejected at submit.
    status, body, _, _ = lane.handle_generate(
        json.dumps({"prompt": "x" * 40, "max_new_tokens": 10}).encode()
    )
    assert status == 400 and b"exceeds" in body
    assert [c[1] for c in slo_spy.calls] == [400, 400]


def test_queue_at_capacity_is_a_retryable_503(lane, slo_spy):
    slo_spy.calls.clear()
    old_cap = lane.scheduler.queue_cap
    lane.scheduler.queue_cap = 0  # every admission is over cap
    try:
        status, body, _, _ = lane.handle_generate(
            json.dumps({"prompt": "hi"}).encode()
        )
    finally:
        lane.scheduler.queue_cap = old_cap
    assert status == 503 and b"capacity" in body
    assert slo_spy.calls == [("gen-test", 503, False)]


def test_budget_violation_counts_as_deadline_exceeded(lane, slo_spy,
                                                      monkeypatch):
    # A completed stream whose TTFT blows the per-token budget is LATE
    # for SLO purposes -- that is what feeds burn rates and the brownout
    # ladder, per-token SLOs being the lane's product surface.
    monkeypatch.setenv(generate_lib.TTFT_BUDGET_ENV, "0.000001")
    slo_spy.calls.clear()
    _, body, _, _ = lane.handle_generate(
        json.dumps({"prompt": "hi", "max_new_tokens": 3,
                    "stream": False}).encode()
    )
    assert json.loads(body)["finish_reason"] in ("stop", "length")
    assert slo_spy.calls == [("gen-test", 200, True)]


def test_debug_payload_has_window_budgets_and_occupancy(lane):
    payload = lane.debug_payload()
    assert payload["model"] == "gen-test"
    assert payload["continuous"] is True
    assert set(payload["budgets_ms"]) == {"ttft", "tpot"}
    w = payload["window"]
    assert w["generations"] >= 1  # earlier tests populated the window
    assert set(w["ttft_ms"]) == {"p50", "p95", "p99"}
    occ = payload["occupancy"]
    assert occ["max_slots"] == 2
    assert occ["active_slots"] == 0 and occ["queue_depth"] == 0
    assert occ["pages_total"] == lane.engine.num_pages - 1
    assert sum(payload["finish_reasons"].values()) == w["generations"]


def test_decode_series_minted_centrally_on_the_lane_registry(lane, registry):
    text = registry.render()
    for series in (
        "kdlt_decode_ttft_seconds",
        "kdlt_decode_tpot_seconds",
        "kdlt_decode_tokens_total",
        "kdlt_decode_generations_total",
        "kdlt_decode_steps_total",
        "kdlt_decode_kv_pages_in_use",
    ):
        assert series in text, series
    assert 'model="gen-test"' in text


def test_client_disconnect_mid_stream_cancels_the_generation(lane,
                                                             monkeypatch):
    # Slow the step down so the stream is demonstrably mid-flight when
    # the client vanishes (full speed would race the close against a
    # finished generation).
    import time as time_lib

    orig_step = lane.engine.step_async

    def slow_step():
        time_lib.sleep(0.01)
        return orig_step()

    monkeypatch.setattr(lane.engine, "step_async", slow_step)
    status, payload, _, _ = lane.handle_generate(
        json.dumps({"prompt": "hi", "max_new_tokens": 25}).encode(),
        rid="gone-1",
    )
    assert status == 200
    it = iter(payload)
    next(it)  # first token is on the wire...
    it.close()  # ...then the client goes away (transport closes the iterator)
    # The finally must cancel the generation so the decode loop frees the
    # slot instead of spending 29 more steps on a gone client.
    deadline = threading.Event()
    for _ in range(300):
        if lane.engine.active_slots == 0 and lane.engine.pages_in_use == 0:
            break
        deadline.wait(0.02)
    assert lane.engine.active_slots == 0
    assert lane.engine.pages_in_use == 0
    assert lane.debug_payload()["finish_reasons"].get("cancelled", 0) >= 1


# --- end-to-end: server route -> gateway relay -> client ---------------------


@pytest.mark.slow
def test_generate_streams_end_to_end_through_gateway_and_client(tmp_path):
    """The full wire path (slow: exports a model, warms two tiers): a
    token stream leaves the model server's ``:generate`` route as
    chunked SSE, relays through the gateway's ``/generate`` without
    buffering or caching, and lands in kdlt-client's incremental parser
    bit-identical to the non-streamed JSON answer."""
    import numpy as np

    from kubernetes_deep_learning_tpu.export import export_model
    from kubernetes_deep_learning_tpu.models import init_variables
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.serving import client as client_lib
    from kubernetes_deep_learning_tpu.serving.gateway import Gateway
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    spec = register_spec(ModelSpec(
        name="gen-e2e-xception", family="xception", input_shape=(96, 96, 3),
        labels=("a", "b"), preprocessing="tf", head_hidden=(8,),
    ))
    root = tmp_path / "models"
    export_model(spec, init_variables(spec, seed=0), str(root),
                 dtype=np.float32)
    server = ModelServer(str(root), port=0, buckets=(1, 2), decode=True)
    server.start()
    gw = Gateway(serving_host=f"localhost:{server.port}", model=spec.name,
                 port=0)
    gw.start()
    base = f"http://localhost:{gw.port}"
    try:
        stats: dict = {}
        events = list(client_lib.generate_stream(
            base, "hello tpu", max_new_tokens=6, stats=stats,
        ))
        done = events[-1]
        assert done["done"] is True and done["tokens"] == 6
        assert stats["request_id"]
        import requests

        r = requests.post(
            f"{base}/generate",
            json={"prompt": "hello tpu", "max_new_tokens": 6,
                  "stream": False},
            timeout=60,
        )
        assert r.status_code == 200
        assert r.json()["text"] == done["text"]  # greedy: same stream
        # Wrong model on the explicit route: 404 passthrough.
        r = requests.post(f"{base}/generate/nope", json={"prompt": "x"},
                          timeout=60)
        assert r.status_code == 404
        # The decode section rides each replica's /debug/slo through the
        # gateway merge -- the data kdlt-client's TTFT/TPOT table renders.
        slo = client_lib.fetch_slo(base)
        decs = [
            body.get("decode") for body in slo["replicas"].values()
            if isinstance(body, dict)
        ]
        assert any(d and d["window"]["generations"] >= 2 for d in decs)
        table = client_lib.render_decode_slo(slo)
        assert "gen-default" in table
    finally:
        gw.shutdown()
        server.shutdown()
