import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import export_model, load_artifact
from kubernetes_deep_learning_tpu.export.artifact import version_dir
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.runtime import InferenceEngine


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

    spec = register_spec(
        ModelSpec(
            name="engine-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
        )
    )
    root = tmp_path_factory.mktemp("models")
    variables = init_variables(spec, seed=1)
    export_model(spec, variables, str(root), dtype=np.float32)
    artifact = load_artifact(version_dir(str(root), spec.name, 1))
    eng = InferenceEngine(artifact, buckets=(1, 2, 4, 8))
    return eng, variables, spec


def test_warmup_sets_ready(engine):
    eng, _, _ = engine
    assert not eng.ready or True  # warmup may already have run in other tests
    dt = eng.warmup()
    assert eng.ready and dt >= 0


def test_padding_does_not_change_results(engine):
    import jax

    eng, variables, spec = engine
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(3, 96, 96, 3), dtype=np.uint8)  # pads to 4
    got = eng.predict(x)
    assert got.shape == (3, 4)
    fwd = jax.jit(build_forward(spec, dtype=None))
    want = np.asarray(fwd(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_bucket_selection(engine):
    eng, _, _ = engine
    assert eng.bucket_for(1) == 1
    assert eng.bucket_for(3) == 4
    assert eng.bucket_for(8) == 8
    with pytest.raises(ValueError):
        eng.bucket_for(9)


def test_engine_flops_per_image_from_lowered_cost_analysis(engine):
    # The live-MFU FLOPs path: lowering-only cost analysis of the exact
    # flax graph (no XLA compile, no device work).  On any backend that
    # supports cost analysis it must produce a positive, batch-normalized
    # figure; None is the accepted degraded answer elsewhere.
    eng, _, _ = engine
    flops = eng._flops_per_image(2)
    assert flops is not None and flops > 0
    # FLOPs/image is ~batch-invariant (same math per row).
    flops1 = eng._flops_per_image(1)
    assert flops1 == pytest.approx(flops, rel=0.2)


def test_mfu_accountant_gauges_and_busy_ratio():
    from kubernetes_deep_learning_tpu.runtime import flops as flops_lib
    from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

    registry = metrics_lib.Registry()
    acct = flops_lib.MfuAccountant(
        registry, peak_tf=1e-9,  # 1000 FLOP/s "device": tiny, predictable
        flops_fn=lambda bucket: 100.0, enabled=True,
    )
    # First observation queues the background FLOPs estimate; wait for it,
    # then observe again so the gauge exists with a value.
    acct.observe(4, 4, 0.5)
    deadline = __import__("time").monotonic() + 5.0
    while not acct.snapshot() and __import__("time").monotonic() < deadline:
        acct.observe(4, 4, 0.5)
        __import__("time").sleep(0.01)
    # 4 rows x 100 FLOP / (0.5 s x 1000 FLOP/s) = 80% MFU.
    assert acct.snapshot()[4] == pytest.approx(80.0, abs=1.0)
    page = registry.render()
    assert 'kdlt_mfu_pct{bucket="4"}' in page
    assert "kdlt_device_busy_ratio" in page


def test_mfu_accountant_disabled_without_peak():
    from kubernetes_deep_learning_tpu.runtime import flops as flops_lib
    from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

    registry = metrics_lib.Registry()
    acct = flops_lib.MfuAccountant(
        registry, peak_tf=None, flops_fn=lambda b: 100.0
    )
    assert acct.enabled is False
    acct.observe(4, 4, 0.1)  # busy accounting still runs; MFU does not
    assert acct.snapshot() == {}
    assert "kdlt_device_busy_ratio" in registry.render()
    assert "kdlt_mfu_pct" not in registry.render()


def test_input_validation(engine):
    eng, _, _ = engine
    with pytest.raises(ValueError, match="expected"):
        eng.predict(np.zeros((1, 10, 10, 3), np.uint8))


def test_predict_scores_labels(engine):
    eng, _, spec = engine
    out = eng.predict_scores(np.zeros((2, 96, 96, 3), np.uint8))
    assert len(out) == 2
    assert set(out[0]) == set(spec.labels)


def test_metrics_populated(engine):
    eng, _, _ = engine
    eng.predict(np.zeros((1, 96, 96, 3), np.uint8))
    text = eng.registry.render()
    assert "kdlt_engine_images_total" in text
    assert "kdlt_engine_infer_seconds" in text


def test_fast_compile_failure_degrades_to_exact_graph(engine):
    """Round-2 P0 regression: a Mosaic compile failure on the fused fast
    path must degrade the engine to the flax graph, not kill the model.

    fast=True on the CPU backend is a REAL reproduction, not a mock: the
    Pallas TPU kernel cannot lower for CPU outside interpret mode, so the
    first warmup bucket raises at compile exactly like BENCH_r02's batch-1
    Mosaic rejection did on TPU.
    """
    _, variables, spec = engine
    import jax

    if jax.default_backend() != "cpu":  # conftest forces cpu; belt and braces
        pytest.skip("reproduction requires a backend where Pallas cannot lower")

    from kubernetes_deep_learning_tpu.export import export_model, load_artifact
    from kubernetes_deep_learning_tpu.export.artifact import version_dir
    import tempfile

    with tempfile.TemporaryDirectory() as root:
        export_model(spec, variables, root, dtype=np.float32)
        artifact = load_artifact(version_dir(root, spec.name, 1))
        eng = InferenceEngine(
            artifact, buckets=(1, 2), use_exported=False, fast=True
        )
        assert eng._fast_engaged
        dt = eng.warmup()
        assert eng.ready and dt >= 0
        assert eng.fast_degraded
        assert not eng._fast_engaged
        # and it actually serves, matching the exact graph
        x = np.zeros((2, *spec.input_shape), np.uint8)
        got = eng.predict(x)
        want = np.asarray(jax.jit(build_forward(spec, dtype=None))(variables, x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_donation_engaged_on_every_bucket(engine):
    # The donation audit's regression surface (ISSUE 9): every bucket's
    # compiled forward donates the batch argument (Lowered.args_info is
    # trace+lower only -- no XLA compile), and NEVER the variables --
    # donating the weights would free them under the next request.
    eng, _, _ = engine
    for b in eng.buckets:
        info = eng.donation_info(b)
        assert info["images"] is True, f"bucket {b}: batch not donated"
        assert info["variables"] is False, f"bucket {b}: variables donated!"


@pytest.mark.slow  # one extra full-engine compile
def test_donated_logits_bit_identical_to_nondonated(engine):
    # Donation is a memory-lifetime annotation, not a numerics change: the
    # same forward jitted WITHOUT donate_argnums must produce bit-identical
    # logits for the same batch.
    import jax
    import jax.numpy as jnp

    eng, _, spec = engine
    x = np.random.default_rng(5).integers(
        0, 256, size=(1, *spec.input_shape), dtype=np.uint8
    )
    donated = eng.predict(x)
    plain = jax.jit(eng._live_forward(jnp.dtype(eng._compute_dtype)))
    want = np.asarray(plain(eng._variables, x))[:1]
    assert np.array_equal(donated, want)


def test_donation_env_kill_switch(monkeypatch):
    # KDLT_DONATE=0 must build a non-donating program (the A/B lever the
    # bit-identity contract above is verified against on real devices).
    from kubernetes_deep_learning_tpu.runtime.engine import donation_enabled

    assert donation_enabled() is True
    monkeypatch.setenv("KDLT_DONATE", "0")
    assert donation_enabled() is False
    monkeypatch.delenv("KDLT_DONATE")
    assert donation_enabled(False) is False
