"""Regression tests for the ADVICE round-5 findings + the bench CLI smoke.

Each of the three fixed findings gets a failing-before/passing-after test,
and --dry-run pins the driver's exact invocation surface so a bench
refactor cannot silently break the official-record command.  Everything
here is device-free: unit-level calls plus fake-child subprocesses (the
same machinery as test_bench_isolation) that never import jax or dial the
single-client TPU tunnel.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "bench.py")


def _bench_module():
    spec = importlib.util.spec_from_file_location("kdlt_bench", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- ADVICE r5 #1: scan-length quantization must respect the 2000 clamp ----


def test_auto_scan_len_never_exceeds_worker_clamp():
    bench = _bench_module()
    # The failing-before shape: any k_raw in (1448, 2000] used to
    # round-to-nearest up to 2^11 = 2048, past the documented worker-safety
    # clamp.  est = 4.0/k_raw inverts the sizing formula exactly.
    for k_raw in (1449.0, 1500.0, 1750.0, 1999.0, 2000.0):
        k = bench.auto_scan_len(4.0 / k_raw)
        assert k <= bench.SCAN_LEN_CAP, (k_raw, k)
    # Quantization itself still works and stays a power of two below the cap.
    assert bench.auto_scan_len(4.0 / 100.0) == 128
    assert bench.auto_scan_len(1.0) == 32  # floor region: k_raw=24 -> 2^5
    # A zero/absurd probe estimate must not divide-by-zero or blow the cap.
    assert 24 <= bench.auto_scan_len(0.0) <= bench.SCAN_LEN_CAP


# --- ADVICE r5 #2: attempt-1 budget skips are trimming, not faults --------


def test_budget_skip_is_recorded_as_dropped_not_fault():
    env = dict(os.environ)
    env["KDLT_BENCH_FAKE_CHILD"] = "1"
    env["KDLT_BENCH_FAKE_CHILD_SLEEP_S"] = "2"
    # Budget window chosen so the per-point pre-check passes (elapsed +
    # 60s floor <= 70) but the attempt-level guard trips (remaining < 90):
    # point 1 runs (~2s), points 2 and 3 hit the attempt-1 skip.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--batches", "4,8,16", "--budget-s", "70"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, timeout=120,
    )
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert len(out["sweep"]) == 1
    # The never-attempted points are budget TRIMMING: dropped, zero faults,
    # and the metric note says trimmed -- not "faulted point attempt(s)".
    assert out["dropped_points"] == [8, 16]
    assert out["faults"] == []
    assert "budget trimmed" in out["metric"]
    assert "faulted" not in out["metric"]
    assert proc.returncode == 0  # the surviving point is in-bound


# --- ADVICE r5 #3: empty-string cache env var means unset, not off --------


def test_compile_cache_empty_env_is_unset_not_disable(monkeypatch):
    from kubernetes_deep_learning_tpu.utils.compilecache import resolve_cache_dir

    monkeypatch.setenv("KDLT_COMPILE_CACHE_DIR", "")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cc")
    # Before the fix "" was a disable sentinel and suppressed the fallback.
    assert resolve_cache_dir() == "/tmp/jax-cc"
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")
    assert resolve_cache_dir(default_dir="/tmp/dflt") == "/tmp/dflt"
    # The explicit sentinels still disable everything downstream...
    for sentinel in ("off", "none", "0", " OFF "):
        monkeypatch.setenv("KDLT_COMPILE_CACHE_DIR", sentinel)
        assert resolve_cache_dir(default_dir="/tmp/dflt") is None
    # ...but never an explicit programmatic argument.
    assert resolve_cache_dir("/tmp/explicit") == "/tmp/explicit"
    # And a real env value still wins over the fallback chain.
    monkeypatch.setenv("KDLT_COMPILE_CACHE_DIR", "/tmp/kdlt-cc")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cc")
    assert resolve_cache_dir() == "/tmp/kdlt-cc"


# --- CLI smoke: the driver's invocation surface must keep parsing ---------


def test_dry_run_parses_the_driver_invocation():
    # The official-record invocation is bare `python bench.py` (plus the
    # KDLT_BENCH_BUDGET_S env); --dry-run must echo the resolved config
    # without importing jax, spawning children, or touching a device.
    env = dict(os.environ)
    env["KDLT_BENCH_BUDGET_S"] = "1140"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--dry-run"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "sweep"
    assert out["model"] == "clothing-model"
    # Headline-first point order and the self-trim budget are part of the
    # survivability contract (VERDICT r4); pin them.
    assert out["batches"][0] == 16 and 256 in out["batches"]
    assert out["budget_s"] == 1140.0
    assert out["isolate"] is True


def test_dry_run_covers_the_auxiliary_modes():
    for flags, mode in (
        (["--soak", "60"], "soak"),
        (["--pipeline-ab", "10"], "pipeline_ab"),
        (["--host-saturation", "5"], "host_saturation"),
        (["--batcher-sweep", "5"], "batcher_sweep"),
        (["--overload-ab", "6"], "overload_ab"),
        (["--chaos-ab", "6"], "chaos_ab"),
        (["--cache-ab", "6"], "cache_ab"),
        (["--crosshost-ab", "30"], "crosshost_ab"),
        (["--mesh-ab", "2"], "mesh_ab"),
        (["--obs-overhead-ab", "5"], "obs_overhead_ab"),
        (["--tenant-ab", "5"], "tenant_ab"),
        (["--incident-ab", "6"], "incident_ab"),
        (["--decode-ab", "16"], "decode_ab"),
        (["--ingest-ab", "120"], "ingest_ab"),
    ):
        proc = subprocess.run(
            [sys.executable, _BENCH, *flags, "--dry-run"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, timeout=60,
        )
        assert proc.returncode == 0
        out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
        assert out["mode"] == mode, flags


# --- admission-control overload A/B: CLI surface smoke --------------------


def test_dry_run_overload_ab_echoes_the_admission_config():
    # The --overload-ab invocation surface (serving.admission's acceptance
    # harness) must keep parsing and echo its resolved knobs without
    # importing jax, binding ports, or spawning servers.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--overload-ab", "6", "--dry-run",
         "--overload-deadline-ms", "450", "--overload-rate-x", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "overload_ab"
    assert out["overload"]["deadline_ms"] == 450.0
    assert out["overload"]["rate_x"] == 3.0
    assert out["overload"]["buckets"] == [1, 2]
    assert out["overload"]["device_ms"] == 100.0


def test_dry_run_chaos_ab_echoes_the_fault_tolerance_config():
    # The --chaos-ab invocation surface (the serving-path fault-tolerance
    # acceptance harness) must keep parsing and echo its resolved knobs.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--chaos-ab", "6", "--dry-run",
         "--chaos-hedge-ms", "80", "--chaos-probe-s", "0.25",
         "--chaos-seed", "7", "--chaos-mode", "stall"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "chaos_ab"
    assert out["chaos"]["hedge_ms"] == 80.0
    assert out["chaos"]["probe_s"] == 0.25
    assert out["chaos"]["seed"] == 7
    assert out["chaos"]["deadline_ms"] == 2000.0
    # The cross-host leader arm (ISSUE 8 satellite): the stall mode must
    # round-trip the CLI.
    assert out["chaos"]["mode"] == "stall"


def test_dry_run_incident_ab_echoes_the_flight_recorder_config():
    # The --incident-ab invocation surface (the incident flight-recorder
    # acceptance harness, GUIDE 10m) must keep parsing and echo its
    # resolved knobs without importing jax, binding ports, or spawning
    # servers.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--incident-ab", "6", "--dry-run",
         "--incident-device-ms", "25", "--incident-rate-rps", "16",
         "--incident-seed", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "incident_ab"
    assert out["incident"]["duration_s"] == 6.0
    assert out["incident"]["device_ms"] == 25.0
    assert out["incident"]["rate_rps"] == 16.0
    assert out["incident"]["seed"] == 3
    assert out["incident"]["deadline_ms"] == 1500.0


def test_dry_run_cache_ab_echoes_the_cache_config():
    # The --cache-ab invocation surface (the gateway cache + singleflight
    # acceptance harness, ISSUE 8) must keep parsing and echo its resolved
    # knobs without importing jax, binding ports, or spawning servers.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--cache-ab", "6", "--dry-run",
         "--cache-zipf-alpha", "1.3", "--cache-universe", "32",
         "--cache-rate-rps", "80", "--cache-probe-n", "12",
         "--cache-seed", "5"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "cache_ab"
    assert out["cache"]["duration_s"] == 6.0
    assert out["cache"]["zipf_alpha"] == 1.3
    assert out["cache"]["universe"] == 32
    assert out["cache"]["rate_rps"] == 80.0
    assert out["cache"]["probe_n"] == 12
    assert out["cache"]["seed"] == 5
    assert out["cache"]["device_ms"] == 50.0
    assert out["cache"]["deadline_ms"] == 800.0


def test_dry_run_crosshost_ab_echoes_the_pipeline_config():
    # The --crosshost-ab invocation surface (the cross-host dispatch
    # pipelining acceptance harness, ISSUE 5) must keep parsing and echo
    # its resolved knobs without importing jax or spawning the fleet.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--crosshost-ab", "40", "--dry-run",
         "--crosshost-ab-batch", "16", "--crosshost-ab-processes", "3",
         "--crosshost-ab-depths", "1,2,4", "--crosshost-ab-host-ms", "5"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "crosshost_ab"
    assert out["crosshost"]["rounds"] == 40
    assert out["crosshost"]["batch"] == 16
    assert out["crosshost"]["processes"] == 3
    assert out["crosshost"]["depths"] == [1, 2, 4]
    assert out["crosshost"]["host_ms"] == 5.0


def test_dry_run_mesh_ab_echoes_the_mesh_config():
    # The --mesh-ab invocation surface (the 2-D named-sharding mesh
    # acceptance harness) must keep parsing and echo its resolved knobs
    # without importing jax or bringing up the 8-way host-platform mesh.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--mesh-ab", "3", "--dry-run",
         "--mesh-size", "64", "--mesh-buckets", "4,8",
         "--mesh-arms", "1,2", "--mesh-tol", "1e-3",
         "--mesh-bytes-slack", "0.2", "--mesh-floor", "0.1"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "mesh_ab"
    assert out["mesh"]["reps"] == 3
    assert out["mesh"]["size"] == 64
    assert out["mesh"]["buckets"] == [4, 8]
    assert out["mesh"]["arms"] == [1, 2]
    assert out["mesh"]["tol"] == 1e-3
    assert out["mesh"]["bytes_slack"] == 0.2
    assert out["mesh"]["floor_frac"] == 0.1


def test_dry_run_decode_ab_echoes_the_decode_config():
    # The --decode-ab invocation surface (the generative lane's
    # continuous-batching acceptance gate, GUIDE 10p) is pinned here
    # without importing jax or compiling the decode ladder.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--decode-ab", "12", "--dry-run",
         "--decode-slots", "2", "--decode-step-ms", "5",
         "--decode-deadline-ms", "1500", "--decode-ttft-budget-ms", "800",
         "--decode-seed", "7"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, timeout=60,
    )
    assert proc.returncode == 0
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "decode_ab"
    assert out["decode"]["requests"] == 12
    assert out["decode"]["slots"] == 2
    assert out["decode"]["step_ms"] == 5.0
    assert out["decode"]["deadline_ms"] == 1500.0
    assert out["decode"]["ttft_budget_ms"] == 800.0
    assert out["decode"]["seed"] == 7


def test_dry_run_multimodel_ab_echoes_the_scheduler_config():
    # The --multimodel-ab invocation surface (the unified scheduler's
    # acceptance harness) must round-trip the CLI.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--multimodel-ab", "5", "--dry-run",
         "--mm-heavy-device-ms", "80", "--mm-light-deadline-ms", "200",
         "--mm-rate-x", "3"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "multimodel_ab"
    assert out["multimodel"]["duration_s"] == 5.0
    assert out["multimodel"]["heavy_device_ms"] == 80.0
    assert out["multimodel"]["light_deadline_ms"] == 200.0
    assert out["multimodel"]["rate_x"] == 3.0
    assert out["multimodel"]["light_rps"] == 40.0


# --- observability-overhead A/B: CLI surface smoke + the 2% bar -----------


def test_dry_run_obs_overhead_ab_echoes_the_observability_config():
    # The --obs-overhead-ab invocation surface (the SLO/attribution/
    # exemplar layer's cost guard) must keep parsing and echo its resolved
    # knobs without importing jax, binding ports, or spawning servers.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--obs-overhead-ab", "4", "--dry-run",
         "--obs-clients", "8", "--obs-device-ms", "1.5", "--obs-rounds", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, timeout=60,
    )
    assert proc.returncode == 0
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "obs_overhead_ab"
    assert out["obs_overhead"]["duration_s"] == 4.0
    assert out["obs_overhead"]["clients"] == 8
    assert out["obs_overhead"]["device_ms"] == 1.5
    assert out["obs_overhead"]["rounds"] == 3


@pytest.mark.slow
def test_obs_overhead_ab_full_layer_costs_at_most_two_percent():
    """ISSUE 7's acceptance bar (slow: several closed-loop HTTP rounds):
    the full observability layer -- SLO windows, exemplars, tail-based
    retention -- holds >= 98% of the observability-off throughput, and the
    on arm proves the layer actually engaged (exemplars on /metrics, the
    model on /debug/slo)."""
    bench = _bench_module()
    out, rc = bench.bench_obs_overhead_ab(
        duration_s=3.0, clients=8, rounds=2
    )
    assert rc == 0, out
    assert out["value"] >= 0.98, out
    assert out["layer_engaged"] is True


@pytest.mark.slow
def test_overload_ab_slo_view_agrees_with_client_ground_truth():
    """The /debug/slo acceptance cross-check: the admission arm's
    server-side SLO window must account every request the open-loop client
    resolved (completions + sheds), and its good count must reconcile with
    the client-side in-deadline 200s.  Exact equality is not required --
    the deadline clock is measured at two different points (client
    scheduled-send vs server header receipt) -- but the counts must agree
    closely, not directionally."""
    bench = _bench_module()
    out, rc = bench.bench_overload_ab(duration_s=4.0)
    assert rc == 0, out
    arm = out["arms"]["admission"]
    slo = arm["slo_view"]
    assert slo is not None, "admission arm must expose /debug/slo"
    row = slo["5m"]
    resolved = arm["completed_200"] + arm["shed_5xx"]
    # Every client-resolved request is in the server's window (the server
    # can additionally have seen requests the client gave up on).
    assert row["total"] >= resolved - 1
    # In-deadline goodput: server-side good within a small tolerance of the
    # client-side in-deadline completions (both clocks run the same budget).
    client_good = round(arm["goodput_rps"] * 4.0)
    assert abs(row["good"] - client_good) <= max(3, 0.1 * client_good), (
        row, arm,
    )


@pytest.mark.slow
def test_multimodel_ab_weighted_beats_fifo_on_worst_model_goodput():
    """ISSUE 6's acceptance bar (slow: two ~4s open-loop arms with
    hundreds of client threads): under mixed 2x load the weighted
    deadline-aware scheduler beats naive FIFO on worst-model in-deadline
    goodput by >= 1.2x, without degrading the overloaded heavy model."""
    bench = _bench_module()
    out, rc = bench.bench_multimodel_ab(duration_s=4.0)
    assert rc == 0, out
    assert out["value"] >= 1.2, out
    arms = out["arms"]
    w, f = arms["weighted_deadline"], arms["fifo"]
    assert w["worst_model_goodput_frac"] > f["worst_model_goodput_frac"]
    # The rescue must come from the doomed backlog, not the heavy model.
    assert (
        w["models"]["mm-heavy"]["goodput_frac"]
        >= 0.8 * f["models"]["mm-heavy"]["goodput_frac"]
    )


@pytest.mark.slow
def test_decode_ab_continuous_wins_goodput_and_stays_bit_exact():
    """ISSUE 17's acceptance bar (slow: compiles the decode ladder and
    runs two timed arms): under a closed burst of mixed-length
    generations with per-request deadlines, continuous (token-boundary)
    admission beats static request-boundary batching on in-deadline
    token goodput, holds TTFT p99 within the lane's budget, and every
    sampled continuous-batch token stream is bit-identical to the same
    prompt decoded solo on the same engine."""
    bench = _bench_module()
    out, rc = bench.bench_decode_ab(n_requests=12, step_ms=10.0,
                                    deadline_ms=2000.0)
    assert rc == 0, out
    arms = out["arms"]
    assert (
        arms["continuous"]["tokens_in_deadline"]
        >= arms["static"]["tokens_in_deadline"]
    ), arms
    assert arms["continuous"]["ttft_p99_ms"] <= out["ttft_budget_ms"], arms
    assert out["bit_exact_vs_solo"] is True
    # The convoy effect is the mechanism: static's TTFT p99 must reflect
    # late waves queuing behind full batch drains.
    assert arms["static"]["ttft_p99_ms"] > arms["continuous"]["ttft_p99_ms"], arms


def test_dry_run_ingest_ab_echoes_the_ingest_config():
    # The --ingest-ab invocation surface (the raw-bytes ingest wire
    # acceptance harness, ISSUE 20) must keep parsing and echo its
    # resolved knobs without importing jax, binding ports, or encoding
    # a single JPEG.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--ingest-ab", "150", "--dry-run",
         "--ingest-size", "512", "--ingest-input", "96",
         "--ingest-clients", "4", "--ingest-seed", "7"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "ingest_ab"
    assert out["ingest"]["images"] == 150
    assert out["ingest"]["source_px"] == 512
    assert out["ingest"]["input_px"] == 96
    assert out["ingest"]["clients"] == 4
    assert out["ingest"]["seed"] == 7


@pytest.mark.slow
def test_ingest_ab_bytes_wire_moves_the_decode_and_keeps_parity():
    """ISSUE 20's acceptance bar (slow: two closed-loop HTTP arms over a
    real gateway + stub model tier): the bytes wire clears >=1.3x img/s
    OR >=2x lower gateway CPU/image, wire bytes/image stay <=1.2x the
    encoded blob, per-image scores are identical across wires, and the
    bytes arm fires zero fallbacks."""
    bench = _bench_module()
    out, rc = bench.bench_ingest_ab(n_images=96, clients=6)
    assert rc == 0, out
    assert out["speedup_img_per_s"] >= 1.3 or out["cpu_ratio"] >= 2.0, out
    assert out["wire_ratio_vs_encoded"] <= 1.2, out
    assert out["parity_identical"] is True, out
    assert out["used_bytes_wire"] is True, out
    assert out["arms"]["bytes"]["errors"] == 0, out
    assert out["arms"]["tensor"]["errors"] == 0, out
    # The tensor arm must not have touched the bytes wire at all.
    assert out["arms"]["tensor"]["bytes_requests"] == 0, out


@pytest.mark.slow
def test_cache_ab_hit_ratio_goodput_and_singleflight_proof():
    """ISSUE 8's acceptance bar (slow: two ~4s open-loop HTTP arms): on a
    Zipf(1.1) workload at ~2x stub-tier capacity, the cache-on arm holds
    hit_ratio >= 0.5 and beats the cache-off arm's in-deadline goodput;
    a probe of N identical concurrent requests produces EXACTLY ONE
    upstream dispatch (singleflight), and a fresh URL's miss-path
    response is bit-identical to the cache-off arm's."""
    bench = _bench_module()
    out, rc = bench.bench_cache_ab(duration_s=4.0)
    assert rc == 0, out
    assert out["hit_ratio"] >= 0.5, out
    assert out["vs_baseline"] > 1.0, out
    assert out["singleflight_upstream_dispatches"] == 1, out
    assert out["miss_bit_identical"] is True, out
    on = out["arms"]["cache_on"]
    assert on["hits"] > 0 and on["misses"] > 0


@pytest.mark.slow
def test_crosshost_ab_pipelined_beats_lockstep():
    """The tentpole's acceptance bar on a REAL 2-process fleet (slow:
    spawns a fleet + compiles): pipelined >= 1.15x lockstep img/s with
    bit-identical logits, depth 1 == lockstep.  Serialized behind the
    fleet flock like every multi-process test."""
    from tests.test_crosshost import _fleet_lock

    bench = _bench_module()
    with _fleet_lock():
        out, rc = bench.bench_crosshost_ab(n_rounds=40, batch=32)
    assert rc == 0, out
    assert all(out["identical_to_lockstep"].values()), out
    assert out["value"] >= 1.15, out


# --- the pipelined-vs-serial A/B acceptance bound -------------------------


def test_pipeline_ab_depth2_closes_the_host_gap():
    """The tentpole's acceptance criterion, in-process (conftest already
    forces the CPU backend): with injected per-stage costs the depth-1
    pipeline pays host+device serially (>=15% above the device-execute
    bound at 3ms host / 10ms device) while depth 2 overlaps the host stage
    and lands within 5% -- with byte-identical, correctly-wired results."""
    bench = _bench_module()
    out, rc = bench.bench_pipeline_ab(
        n_batches=60, batch=8, host_ms=3.0, device_ms=10.0, depths=(1, 2)
    )
    assert rc == 0, out
    assert out["identical_across_depths"] is True
    d1, d2 = out["depths"]["1"], out["depths"]["2"]
    assert d1["miswired_futures"] == 0 and d2["miswired_futures"] == 0
    assert d1["gap_vs_device_bound"] >= 0.15, d1
    assert d2["gap_vs_device_bound"] <= 0.05, d2
    assert out["value"] > 1.1  # wall-clock speedup from pipelining alone


# --- full-int8 quantization A/B (ISSUE 9) ---------------------------------


def test_dry_run_quant_ab_echoes_the_quant_config():
    proc = subprocess.run(
        [sys.executable, _BENCH, "--quant-ab", "3", "--quant-size", "48",
         "--quant-buckets", "1,4", "--quant-calib-images", "16",
         "--quant-min-size", "500000", "--dry-run"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, timeout=60,
    )
    assert proc.returncode == 0
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["mode"] == "quant_ab"
    q = out["quant"]
    assert q["reps"] == 3
    assert q["size"] == 48
    assert q["buckets"] == [1, 4]
    assert q["calib_images"] == 16
    assert q["min_size"] == 500000


# --- tenant isolation + brownout A/B (ISSUE 12) ---------------------------


def test_dry_run_tenant_ab_echoes_the_isolation_config():
    # The --tenant-ab invocation surface (per-model budgets + brownout
    # acceptance harness) must keep parsing and echo its resolved knobs
    # without importing jax, binding ports, or spawning servers.
    proc = subprocess.run(
        [sys.executable, _BENCH, "--tenant-ab", "5", "--dry-run",
         "--tenant-device-ms", "40", "--tenant-deadline-ms", "1200",
         "--tenant-rate-x", "2.5", "--tenant-b-rps", "10",
         "--tenant-flood-s", "4", "--tenant-seed", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert out["dry_run"] is True
    assert out["mode"] == "tenant_ab"
    t = out["tenant"]
    assert t["duration_s"] == 5.0
    assert t["device_ms"] == 40.0
    assert t["deadline_ms"] == 1200.0
    assert t["rate_x"] == 2.5
    assert t["b_rps"] == 10.0
    assert t["flood_s"] == 4.0
    assert t["seed"] == 3


@pytest.mark.slow
def test_tenant_ab_budgets_isolate_and_brownout_recovers():
    """ISSUE 12's acceptance bar (slow: two open-loop model-tier arms plus
    a gateway brownout arm with a best-effort flood): with per-model
    budgets, victim tenant-b holds >= 95% in-deadline goodput while
    tenant-a floods at 3x capacity, vs collapse under the shared limiter;
    the brownout ladder then climbs to >= stage 3 under the flood, keeps
    interactive goodput >= 95%, recovers the 5m burn below 1.0, and walks
    back down with ZERO up/down flaps."""
    bench = _bench_module()
    out, rc = bench.bench_tenant_ab(duration_s=4.0)
    assert rc == 0, out
    assert out["part1_ok"] is True, out
    assert out["part2_ok"] is True, out
    b_budget = out["arms"]["budgets"]["models"]["tenant-b"]["goodput_frac"]
    b_shared = out["arms"]["shared"]["models"]["tenant-b"]["goodput_frac"]
    assert b_budget >= 0.95, out["arms"]["budgets"]
    assert b_shared < 0.8 * b_budget, out["arms"]["shared"]
    arm = out["brownout_arm"]
    assert arm["classes"]["interactive"]["goodput_frac"] >= 0.95, arm
    assert arm["peak_stage"] >= 3, arm
    assert arm["burn_final"] < 1.0, arm
    assert arm["flap_free"] is True, arm
    # The flood was actually shed by the ladder, not absorbed.
    assert arm["classes"]["best-effort"]["shed_429"] > 0, arm


@pytest.mark.slow
def test_quant_ab_w8a8_beats_f32_on_proxy_within_tolerance():
    """ISSUE 9's acceptance bar (slow: three engine warmups incl. the CPU
    int8 reference lowering): w8a8 >= 1.2x f32 img/s on the v5e roofline
    proxy at the smallest bucket, top-1 agreement >= 0.99 and max-abs
    logit drift within KDLT_QUANT_TOL on the golden fixture, and the
    engine's own warmup tolerance gate ACCEPTED the calibrated artifact
    (measured CPU img/s is reported alongside -- XLA:CPU has no s8xs8
    fast path, so the device claim rides the proxy + the gate numerics)."""
    bench = _bench_module()
    out, rc = bench.bench_quant_ab(
        reps=2, size=32, buckets=(1, 2), calib_images=16,
        percentile=100.0, min_size=700_000,
    )
    assert rc == 0, out
    assert out["value"] >= 1.2, out
    assert out["gate_accepted"] is True, out
    assert out["top1_agreement"] >= 0.99, out
    assert out["worst_rel_maxabs_drift"] <= out["tol"], out
    # Weight bytes: the roofline's numerator is real, not assumed.  This
    # config confines int8 to the three biggest kernels (CPU economy), so
    # the drop is partial; the full-ladder ~4x is pinned by
    # test_quantize.py's artifact-size assertion.
    f32_b = next(iter(out["arms"]["f32"]["buckets"].values()))["weight_bytes"]
    w8a8_b = next(iter(out["arms"]["w8a8"]["buckets"].values()))["weight_bytes"]
    assert w8a8_b < f32_b * 0.85, (f32_b, w8a8_b)
