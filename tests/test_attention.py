"""Flash-attention kernel + partial-merge algebra vs reference softmax."""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp

from kubernetes_deep_learning_tpu.ops.attention import (
    attend_block,
    combine_partials,
    finalize_partials,
    flash_attention,
    mha_reference,
)


def _rand_qkv(rng, b=2, h=2, s=256, d=64, dtype=np.float32):
    shape = (b, h, s, d)
    return tuple(rng.standard_normal(shape).astype(dtype) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference_f32(causal):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_bf16_close_to_f32_reference():
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng)
    got = flash_attention(
        *(x.astype(jnp.bfloat16) for x in (q, k, v)), causal=False, interpret=True
    )
    want = mha_reference(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), atol=0.05, rtol=0.05
    )


def test_flash_rejects_ragged_seq():
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, s=100)
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, k, v, interpret=True)


@pytest.mark.parametrize("causal", [False, True])
def test_partial_merge_equals_full(causal):
    """Splitting KV into blocks and lse-merging partials is exact."""
    rng = np.random.default_rng(3)
    q, k, v = _rand_qkv(rng, s=128)
    half = 64
    p1 = attend_block(q, k[..., :half, :], v[..., :half, :], causal=causal, k_offset=0)
    p2 = attend_block(q, k[..., half:, :], v[..., half:, :], causal=causal, k_offset=half)
    got = finalize_partials(combine_partials(p1, p2))
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_fully_masked_rows_are_zero():
    """causal + k_offset beyond the sequence: every key is in the future of
    every query; empty softmax is defined as zeros, not mean(v)."""
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, s=128)
    got = flash_attention(q, k, v, causal=True, k_offset=10_000, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.zeros_like(got))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_partials_match_attend_block(causal):
    """Partial-output kernel returns the same (acc, m, l) algebra as the
    reference einsum path, so ring attention can swap one for the other."""
    rng = np.random.default_rng(6)
    q, k, v = _rand_qkv(rng, s=128)
    ref = attend_block(q, k, v, causal=causal, k_offset=0)
    got = flash_attention(
        q, k, v, causal=causal, block_q=64, block_k=64,
        interpret=True, return_partials=True,
    )
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(ref[1]), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(finalize_partials(got)),
        np.asarray(finalize_partials(ref)),
        atol=2e-5, rtol=2e-5,
    )


def test_flash_partials_merge_across_kv_shards():
    """lse-merging two flash partials over split KV equals full attention --
    the exact composition ring attention performs."""
    rng = np.random.default_rng(7)
    q, k, v = _rand_qkv(rng, s=128)
    half = 64
    p1 = flash_attention(
        q, k[..., :half, :], v[..., :half, :], causal=True,
        block_q=64, block_k=64, interpret=True, return_partials=True,
    )
    # Remote "past" shard in ring order: fully visible, no mask needed.
    p2 = flash_attention(
        q, k[..., half:, :], v[..., half:, :], causal=True, k_offset=half,
        block_q=64, block_k=64, interpret=True, return_partials=True,
    )
    got = finalize_partials(combine_partials(p1, p2))
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_causal_negative_offset_matches_reference():
    """KV shard from the past (ring attention): every row partially visible,
    so flash and plain softmax agree everywhere."""
    rng = np.random.default_rng(8)
    q, k, v = _rand_qkv(rng, s=128)
    got = flash_attention(q, k, v, causal=True, k_offset=-64, interpret=True)
    want = mha_reference(q, k, v, causal=True, k_offset=-64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_causal_positive_offset_bounded_stream():
    """KV shard shifted into the future: visible rows must stay exact under
    the diagonal-bounded KV stream; fully-masked rows are defined as zero."""
    rng = np.random.default_rng(9)
    q, k, v = _rand_qkv(rng, s=128)
    got = np.asarray(
        flash_attention(q, k, v, causal=True, k_offset=64, interpret=True)
    )
    want = np.asarray(mha_reference(q, k, v, causal=True, k_offset=64))
    # Rows 0..63 see no keys (key j sits at global position j+64): zeros.
    np.testing.assert_array_equal(got[..., :64, :], np.zeros_like(got[..., :64, :]))
    np.testing.assert_allclose(got[..., 64:, :], want[..., 64:, :], atol=2e-5, rtol=2e-5)


def test_finalize_zero_l_rows_are_zero_not_nan():
    """A flash partial over a fully-masked shard carries l=0; finalizing it
    directly must yield zeros (the empty-softmax convention), not 0/0."""
    rng = np.random.default_rng(10)
    q, k, v = _rand_qkv(rng, s=128)
    p = flash_attention(
        q, k, v, causal=True, k_offset=10_000, interpret=True, return_partials=True
    )
    out = np.asarray(finalize_partials(p))
    np.testing.assert_array_equal(out, np.zeros_like(out))


def test_fully_masked_block_is_neutral_in_merge():
    """A KV block entirely in the causal future must not perturb the merge."""
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, s=64)
    real = attend_block(q, k, v, causal=True, k_offset=0)
    # Block whose every key is in the future of every query.
    future = attend_block(q, k, v, causal=True, k_offset=10_000)
    got = finalize_partials(combine_partials(real, future))
    want = finalize_partials(real)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


@pytest.mark.parametrize("seq", [257, 13, 100])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_padded_ragged_seq(seq, causal):
    """Ragged sequence lengths (no 8-aligned divisor, e.g. ViT's prime 257
    tokens) run the flash kernel via pad + kv_len masking and must match
    the einsum reference exactly on the real rows."""
    from kubernetes_deep_learning_tpu.ops.attention import (
        flash_attention_padded,
        mha_reference,
    )

    rng = np.random.default_rng(seq)
    shape = (2, 3, seq, 16)
    q = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, shape), jnp.float32)
    got = np.asarray(flash_attention_padded(q, k, v, causal=causal))
    want = np.asarray(mha_reference(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_attention_serving_routing_and_equivalence():
    """Shape routing (round 4): einsum while S <= EINSUM_MAX_SEQ, flash
    past it; the einsum route must be exactly mha_reference."""
    import numpy as np

    from kubernetes_deep_learning_tpu.ops.attention import (
        EINSUM_MAX_SEQ,
        attention_serving,
        mha_reference,
        use_einsum_attention,
    )

    assert use_einsum_attention(256, 256)
    assert use_einsum_attention(EINSUM_MAX_SEQ, EINSUM_MAX_SEQ)
    assert not use_einsum_attention(EINSUM_MAX_SEQ + 8, EINSUM_MAX_SEQ)
    assert not use_einsum_attention(1024, 1024)

    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 16, 8)), jnp.float32)
        for _ in range(3)
    )
    got = np.asarray(attention_serving(q, k, v))
    want = np.asarray(mha_reference(q, k, v))
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_flash_attention_bf16_dots_match_reference():
    """The bf16 in-kernel dot path (the dtype production serving runs --
    f32 softmax statistics, bf16 MXU operands, round 4) must stay at
    bf16-noise distance from the f32 reference on the same data."""
    import numpy as np

    from kubernetes_deep_learning_tpu.ops.attention import (
        flash_attention,
        mha_reference,
    )

    rng = np.random.default_rng(7)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 3, 256, 64)), jnp.float32)
        for _ in range(3)
    )
    want = np.asarray(mha_reference(q, k, v), np.float32)
    got = np.asarray(
        flash_attention(
            q.astype(jnp.bfloat16),
            k.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16),
            block_q=128,
            block_k=128,
            interpret=True,
        ),
        np.float32,
    )
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 2e-2, f"bf16 flash dots diverge from f32 reference: {rel:.2e}"


def test_flash_attention_padded_cross_attention_ragged():
    """sq != sk must pad each side independently (a q-derived pad on k
    either misaligns or crashes the kernel's divisibility check)."""
    import numpy as np

    from kubernetes_deep_learning_tpu.ops.attention import (
        flash_attention_padded,
        mha_reference,
    )

    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((1, 2, 250, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 2, 520, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 2, 520, 16)), jnp.float32)
    got = np.asarray(flash_attention_padded(q, k, v, interpret=True))
    want = np.asarray(mha_reference(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    # Tileable-but-unequal lengths take the unpadded fast exit.
    q2 = jnp.asarray(rng.standard_normal((1, 2, 512, 16)), jnp.float32)
    got2 = np.asarray(flash_attention_padded(q2, k[:, :, :640], v[:, :, :640], interpret=True))
    want2 = np.asarray(mha_reference(q2, k[:, :, :640], v[:, :, :640]))
    np.testing.assert_allclose(got2, want2, rtol=2e-4, atol=2e-4)
