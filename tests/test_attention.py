"""Flash-attention kernel + partial-merge algebra vs reference softmax."""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp

from kubernetes_deep_learning_tpu.ops.attention import (
    attend_block,
    combine_partials,
    finalize_partials,
    flash_attention,
    mha_reference,
)


def _rand_qkv(rng, b=2, h=2, s=256, d=64, dtype=np.float32):
    shape = (b, h, s, d)
    return tuple(rng.standard_normal(shape).astype(dtype) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference_f32(causal):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_bf16_close_to_f32_reference():
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng)
    got = flash_attention(
        *(x.astype(jnp.bfloat16) for x in (q, k, v)), causal=False, interpret=True
    )
    want = mha_reference(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), atol=0.05, rtol=0.05
    )


def test_flash_rejects_ragged_seq():
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, s=100)
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, k, v, interpret=True)


@pytest.mark.parametrize("causal", [False, True])
def test_partial_merge_equals_full(causal):
    """Splitting KV into blocks and lse-merging partials is exact."""
    rng = np.random.default_rng(3)
    q, k, v = _rand_qkv(rng, s=128)
    half = 64
    p1 = attend_block(q, k[..., :half, :], v[..., :half, :], causal=causal, k_offset=0)
    p2 = attend_block(q, k[..., half:, :], v[..., half:, :], causal=causal, k_offset=half)
    got = finalize_partials(combine_partials(p1, p2))
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_fully_masked_rows_are_zero():
    """causal + k_offset beyond the sequence: every key is in the future of
    every query; empty softmax is defined as zeros, not mean(v)."""
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, s=128)
    got = flash_attention(q, k, v, causal=True, k_offset=10_000, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.zeros_like(got))


def test_fully_masked_block_is_neutral_in_merge():
    """A KV block entirely in the causal future must not perturb the merge."""
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, s=64)
    real = attend_block(q, k, v, causal=True, k_offset=0)
    # Block whose every key is in the future of every query.
    future = attend_block(q, k, v, causal=True, k_offset=10_000)
    got = finalize_partials(combine_partials(real, future))
    want = finalize_partials(real)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
