"""Serving-path fault tolerance through the real tiers (stub backend):
multi-replica failover when a replica dies mid-run, active-probe recovery,
budget-aware hedged requests, the engine watchdog failing hung dispatches
and flipping health, per-replica spec re-validation on failover, and the
client's connect-error retries.  All device-free."""

from __future__ import annotations

import re
import threading
import time

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
from kubernetes_deep_learning_tpu.serving import faults, protocol
from kubernetes_deep_learning_tpu.serving.admission import Deadline
from kubernetes_deep_learning_tpu.serving.gateway import Gateway, UpstreamError
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
from kubernetes_deep_learning_tpu.serving.upstream import UpstreamPool


def _metric(text: str, name: str, **labels: str) -> float:
    for m in re.finditer(rf"^{re.escape(name)}(\{{[^}}]*\}})? (\S+)$", text, re.M):
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1) or ""))
        if all(got.get(k) == v for k, v in labels.items()):
            return float(m.group(2))
    raise AssertionError(f"no sample {name} with {labels} in:\n{text}")


def _make_stub_server(
    name, tmp_path, subdir="models", device_ms=0.0, labels=("a", "b", "c"), **kw
):
    spec = register_spec(
        ModelSpec(
            name=name,
            family="xception",  # never instantiated by StubEngine
            input_shape=(32, 32, 3),
            labels=tuple(labels),
        )
    )
    root = tmp_path / subdir
    art.save_artifact(
        art.version_dir(str(root), spec.name, 1), spec, {"params": {}}, None, {}
    )
    factory = kw.pop("engine_factory", None) or (
        lambda a, **ekw: StubEngine(a, device_ms_per_batch=device_ms, **ekw)
    )
    server = ModelServer(
        str(root), port=kw.pop("port", 0), buckets=kw.pop("buckets", (1, 2)),
        max_delay_ms=1.0, host="127.0.0.1", engine_factory=factory, **kw,
    )
    server.warmup()
    server.start()
    return spec, server


def _hard_kill(server) -> None:
    """The chaos kill: in-flight/keep-alive predicts drop their connection
    (injected disconnect) and the listener closes, so new connects --
    including health probes -- are refused.  shutdown() alone is not a kill:
    pooled keep-alive sockets keep being served by their handler threads."""
    server._faults = faults.FaultInjector(
        faults.parse_rules("server.predict:disconnect:1.0")
    )
    server.shutdown()


IMG = np.zeros((1, 32, 32, 3), np.uint8)


# --- pool unit behavior -----------------------------------------------------


def test_pool_round_robins_and_prefers_healthy():
    pool = UpstreamPool(["h1:1", "h2:2"], failover=True, probe_interval_s=0)
    a, b = pool.replicas
    first = pool.choose()
    second = pool.choose()
    assert {first, second} == {a, b}  # round-robin spreads load
    # Two consecutive failures mark a replica unhealthy and route around it.
    pool.record_failure(a)
    pool.record_failure(a)
    assert not a.healthy
    assert pool.choose() is b and pool.choose() is b
    # ...but it stays reachable as a last resort (breaker-gated recovery).
    assert pool.choose(exclude=[b]) is a
    pool.record_success(a)
    assert a.healthy


def test_pool_blind_mode_ignores_health():
    pool = UpstreamPool(["h1:1", "h2:2"], failover=False, probe_interval_s=0)
    a, b = pool.replicas
    for _ in range(3):
        pool.record_failure(a)
    got = {pool.choose() for _ in range(4)}
    assert got == {a, b}  # dead or alive, every replica takes its turn
    assert not pool.has_healthy_candidate(exclude=[b])


def test_pool_mark_stalled_takes_replica_out_on_first_observation():
    """ISSUE 8 satellite (ROADMAP cross-host gap #1): a DECLARED dispatch
    stall (the model tier's X-Kdlt-Stalled 503) is terminal until restart,
    so one observation suffices -- unlike ordinary failures, which take
    UNHEALTHY_AFTER consecutive ones."""
    pool = UpstreamPool(["h1:1", "h2:2"], failover=True, probe_interval_s=0)
    a, b = pool.replicas
    # One ORDINARY failure does not unhealth a replica...
    pool.record_failure(a)
    assert a.healthy
    pool.record_success(a)
    # ...but one declared stall does, immediately.
    pool.mark_stalled(a)
    assert not a.healthy
    assert pool.choose() is b and pool.choose() is b
    # The stall mark is sticky against the consecutive-failure reset
    # logic: only an actual health-probe rejoin brings it back.
    assert not pool.has_healthy_candidate(exclude=[b])
    pool.record_success(a)  # e.g. the prober's rejoin path
    assert a.healthy


def test_pool_parse_hosts():
    from kubernetes_deep_learning_tpu.serving.upstream import parse_hosts

    assert parse_hosts("a:1, b:2,a:1,") == ["a:1", "b:2"]
    with pytest.raises(ValueError):
        parse_hosts(" , ")


# --- failover through the real gateway --------------------------------------


def test_gateway_fails_over_to_surviving_replica(tmp_path):
    spec, victim = _make_stub_server("fo-live", tmp_path, subdir="a")
    _, survivor = _make_stub_server("fo-live", tmp_path, subdir="b")
    gw = Gateway(
        serving_host=f"127.0.0.1:{victim.port},127.0.0.1:{survivor.port}",
        model=spec.name, port=0, bind=False, probe_interval_s=0.2,
    )
    try:
        gw.spec  # discover the reference contract while both are alive
        _hard_kill(victim)
        # Every request succeeds: dialing the dead replica fails over
        # in-request to the survivor.
        for _ in range(4):
            logits, labels = gw._predict_batch(IMG)
            assert list(labels) == ["a", "b", "c"]
            assert np.asarray(logits).shape == (1, 3)
        metrics = gw.registry.render()
        assert _metric(metrics, "kdlt_upstream_failover_total") >= 1
        assert _metric(
            metrics, "kdlt_upstream_replica_healthy",
            replica=f"127.0.0.1:{victim.port}",
        ) == 0.0
        assert _metric(
            metrics, "kdlt_upstream_replica_healthy",
            replica=f"127.0.0.1:{survivor.port}",
        ) == 1.0
    finally:
        gw.shutdown()
        survivor.shutdown()


def test_prober_rejoins_recovered_replica(tmp_path):
    spec, victim = _make_stub_server("fo-rejoin", tmp_path, subdir="a")
    _, survivor = _make_stub_server("fo-rejoin", tmp_path, subdir="b")
    victim_port = victim.port
    gw = Gateway(
        serving_host=f"127.0.0.1:{victim_port},127.0.0.1:{survivor.port}",
        model=spec.name, port=0, bind=False, probe_interval_s=0.1,
    )
    revived = None
    try:
        gw.spec
        _hard_kill(victim)
        gw._predict_batch(IMG)  # trips passive health marking
        gw._predict_batch(IMG)
        victim_replica = gw.pool.replicas[0]
        assert not victim_replica.healthy
        # Revive a replica on the SAME port; the active prober must rejoin
        # it within a probe interval or two.
        _, revived = _make_stub_server(
            "fo-rejoin", tmp_path, subdir="a2", port=victim_port
        )
        deadline = time.monotonic() + 5.0
        while not victim_replica.healthy and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim_replica.healthy, "prober never rejoined the replica"
        # The rejoined replica's spec was re-validated (fresh fetch).
        gw.pool._rr = 0  # next choose targets the rejoined replica
        logits, _ = gw._predict_batch(IMG)
        assert np.asarray(logits).shape == (1, 3)
    finally:
        gw.shutdown()
        survivor.shutdown()
        if revived is not None:
            revived.shutdown()


def test_spec_mismatch_on_failover_surfaces_as_502(tmp_path):
    # Replica B serves the same model NAME with a different contract
    # (different labels): failover must 502 loudly, not mix responses.
    spec, good = _make_stub_server("fo-spec", tmp_path, subdir="a")
    _, bad = _make_stub_server(
        "fo-spec", tmp_path, subdir="b", labels=("x", "y", "z")
    )
    gw = Gateway(
        serving_host=f"127.0.0.1:{good.port},127.0.0.1:{bad.port}",
        model=spec.name, port=0, bind=False, probe_interval_s=0,
    )
    try:
        gw.pool._rr = 0
        gw.spec  # reference contract discovered from the good replica
        assert gw.pool.reference_spec.labels == ("a", "b", "c")
        _hard_kill(good)
        with pytest.raises(UpstreamError) as exc:
            gw._predict_batch(IMG)
        assert exc.value.http_status == 502
        assert "different model contract" in str(exc.value)
        # The mismatching replica is routed around from now on.
        assert not gw.pool.replicas[1].healthy
    finally:
        gw.shutdown()
        bad.shutdown()


def test_gateway_upstream_fault_point_counts_and_exhausts_pool(
    tmp_path, monkeypatch
):
    # gateway.upstream:error:1.0 faults EVERY upstream attempt: the gateway
    # fails over through the whole pool, then surfaces a retryable 5xx --
    # and every injection is visible on the gateway's own /metrics.
    spec, a = _make_stub_server("gw-fault", tmp_path, subdir="a")
    _, b = _make_stub_server("gw-fault", tmp_path, subdir="b")
    monkeypatch.setenv(faults.FAULTS_ENV, "gateway.upstream:error:1.0")
    gw = Gateway(
        serving_host=f"127.0.0.1:{a.port},127.0.0.1:{b.port}",
        model=spec.name, port=0, bind=False, probe_interval_s=0,
    )
    try:
        gw.spec  # discovery GETs are not a fault point; only predicts are
        with pytest.raises(UpstreamError) as exc:
            gw._predict_batch(IMG)
        assert exc.value.http_status >= 500
        assert "injected fault" in str(exc.value)
        assert _metric(
            gw.registry.render(), "kdlt_fault_injected_total",
            point="gateway.upstream", kind="error",
        ) == 2.0  # one per replica attempt: the pool was actually swept
    finally:
        gw.shutdown()
        a.shutdown()
        b.shutdown()


# --- hedged requests --------------------------------------------------------


def test_hedge_fires_when_budget_allows_and_wins(tmp_path):
    spec, slow = _make_stub_server(
        "hedge-ab", tmp_path, subdir="a", device_ms=500.0
    )
    _, fast = _make_stub_server("hedge-ab", tmp_path, subdir="b")
    gw = Gateway(
        serving_host=f"127.0.0.1:{slow.port},127.0.0.1:{fast.port}",
        model=spec.name, port=0, bind=False,
        hedge_delay_ms=50.0, probe_interval_s=0,
    )
    try:
        gw.spec
        gw.pool._rr = 0  # primary = the slow replica
        t0 = time.perf_counter()
        logits, _ = gw._predict_batch(IMG, deadline=Deadline(5.0))
        dt = time.perf_counter() - t0
        assert np.asarray(logits).shape == (1, 3)
        assert dt < 0.45, f"hedge should beat the 500ms primary, took {dt:.3f}s"
        metrics = gw.registry.render()
        assert _metric(metrics, "kdlt_hedge_fired_total") == 1.0
        assert _metric(metrics, "kdlt_hedge_won_total") == 1.0
    finally:
        gw.shutdown()
        slow.shutdown()
        fast.shutdown()


def test_hedge_skipped_when_budget_cannot_cover_it(tmp_path):
    spec, slow = _make_stub_server(
        "hedge-budget", tmp_path, subdir="a", device_ms=300.0
    )
    _, fast = _make_stub_server("hedge-budget", tmp_path, subdir="b")
    gw = Gateway(
        serving_host=f"127.0.0.1:{slow.port},127.0.0.1:{fast.port}",
        model=spec.name, port=0, bind=False,
        hedge_delay_ms=50.0, probe_interval_s=0,
    )
    try:
        gw.spec
        gw.pool._rr = 0  # primary = the slow replica
        # Budget below hedge_delay + floor: the hedge must NOT fire -- it
        # would be spent work that cannot finish either.
        with pytest.raises(UpstreamError):
            gw._predict_batch(IMG, deadline=Deadline(0.08))
        assert _metric(gw.registry.render(), "kdlt_hedge_fired_total") == 0.0
    finally:
        gw.shutdown()
        slow.shutdown()
        fast.shutdown()


# --- engine watchdog --------------------------------------------------------


def test_watchdog_fails_hung_dispatch(tmp_path, monkeypatch):
    from types import SimpleNamespace

    from kubernetes_deep_learning_tpu.runtime import (
        DispatchStall,
        InFlightDispatcher,
    )

    monkeypatch.setenv(faults.FAULTS_ENV, "dispatch.complete:hang:1.0:60")
    spec = register_spec(
        ModelSpec(
            name="wd-unit", family="xception",
            input_shape=(32, 32, 3), labels=("a", "b", "c"),
        )
    )
    engine = StubEngine(
        SimpleNamespace(spec=spec), buckets=(1, 2),
        device_ms_per_batch=1.0, async_device=True,
    )
    disp = InFlightDispatcher(engine, depth=2, stall_floor_s=0.2)
    try:
        fut = disp.submit(IMG)
        with pytest.raises(DispatchStall):
            fut.result(timeout=10.0)
        assert disp.stalled
        # After the stall: intake fails fast and retryably, no hang.
        with pytest.raises(DispatchStall):
            disp.submit(IMG)
    finally:
        t0 = time.perf_counter()
        disp.close()  # must not wait out the 60s hang
        assert time.perf_counter() - t0 < 5.0
        engine.close()


def test_watchdog_stall_flips_health_endpoints(tmp_path, monkeypatch):
    import requests

    monkeypatch.setenv(faults.FAULTS_ENV, "dispatch.complete:hang:1.0:60")
    monkeypatch.setenv("KDLT_WATCHDOG_FLOOR_S", "0.3")
    spec, server = _make_stub_server(
        "wd-health", tmp_path, device_ms=1.0,
        engine_factory=lambda a, **kw: StubEngine(
            a, device_ms_per_batch=1.0, async_device=True, **kw
        ),
        pipeline_depth=2, use_batcher=False,
    )
    base = f"http://127.0.0.1:{server.port}"
    try:
        assert requests.get(f"{base}/healthz", timeout=5).status_code == 200
        # A 4-image request rides the chunked dispatcher path (buckets max
        # 2); the injected hang wedges its completion, the watchdog fails
        # the futures, and the handler maps it to a retryable 503.
        img = np.zeros((4, *spec.input_shape), np.uint8)
        r = requests.post(
            f"{base}/v1/models/{spec.name}:predict",
            data=protocol.encode_predict_request(img),
            headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
            timeout=30.0,
        )
        assert r.status_code == 503
        assert "stalled" in r.json()["error"]
        assert "Retry-After" in r.headers
        # Liveness AND readiness follow: the orchestrator restarts the pod,
        # the endpoint pool drops it, the gateway's prober routes around it.
        r = requests.get(f"{base}/healthz", timeout=5)
        assert (r.status_code, r.text) == (503, "dispatch stalled")
        assert requests.get(f"{base}/readyz", timeout=5).status_code == 503
        metrics = requests.get(f"{base}/metrics", timeout=5).text
        assert _metric(
            metrics, "kdlt_dispatch_stall_total",
            model=spec.name, version="1",
        ) >= 1.0
    finally:
        server.shutdown()


def test_watchdog_leaves_healthy_pipeline_alone(tmp_path, monkeypatch):
    from types import SimpleNamespace

    from kubernetes_deep_learning_tpu.runtime import InFlightDispatcher

    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    spec = register_spec(
        ModelSpec(
            name="wd-clean", family="xception",
            input_shape=(32, 32, 3), labels=("a", "b", "c"),
        )
    )
    engine = StubEngine(
        SimpleNamespace(spec=spec), buckets=(1, 2),
        device_ms_per_batch=5.0, async_device=True,
    )
    disp = InFlightDispatcher(engine, depth=2, stall_floor_s=0.5)
    try:
        futs = [disp.submit(IMG) for _ in range(6)]
        rows = [np.asarray(f.result(timeout=10)) for f in futs]
        assert all(r.shape == (1, 3) for r in rows)
        assert not disp.stalled
    finally:
        disp.close()
        engine.close()


# --- client connect-error retries -------------------------------------------


def test_client_retries_connect_errors_with_distinct_label():
    import socket

    import requests

    from kubernetes_deep_learning_tpu.serving.client import predict_url

    # A port that was just closed: connects are refused deterministically.
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    stats: dict = {}
    t0 = time.monotonic()
    with pytest.raises(requests.ConnectionError):
        predict_url(
            f"http://127.0.0.1:{port}", "http://x/img.png",
            timeout=10.0, retries=2, stats=stats,
        )
    assert stats["retried_connect"] == 2  # labeled distinctly from sheds
    assert stats["retried_shed"] == 0
    assert time.monotonic() - t0 < 5.0  # jittered short backoffs, bounded


def test_client_connect_retry_bounded_by_timeout():
    import socket

    import requests

    from kubernetes_deep_learning_tpu.serving.client import predict_url

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    stats: dict = {}
    with pytest.raises(requests.ConnectionError):
        # A budget smaller than any backoff sleep: no retry is affordable,
        # the connect error surfaces immediately.
        predict_url(
            f"http://127.0.0.1:{port}", "http://x/img.png",
            timeout=0.01, retries=5, stats=stats,
        )
    assert stats["retried_connect"] == 0


# --- the chaos A/B acceptance harness ---------------------------------------


def test_chaos_ab_failover_holds_goodput_and_baseline_collapses():
    """The PR acceptance numbers, asserted with deterministic seeds: with
    failover+hedging ON, >= 95% of post-kill requests succeed in-deadline
    and recovery completes within one probe interval; with it OFF, success
    collapses toward the single-replica share."""
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench

    out, rc = bench.bench_chaos_ab(
        duration_s=3.0, rate_rps=20.0, device_ms=20.0,
        deadline_ms=2000.0, hedge_delay_ms=100.0, probe_interval_s=0.5,
        seed=0,
    )
    on = out["arms"]["failover_on"]
    off = out["arms"]["failover_off"]
    assert rc == 0, out
    assert on["post_kill_in_deadline_rate"] >= 0.95
    assert on["recovery_s"] <= out["probe_interval_s"] + 0.5
    assert off["post_kill_in_deadline_rate"] < 0.85
    assert on["failover_total"] >= 1


@pytest.mark.slow
def test_chaos_ab_stall_leader_arm_marks_out_on_first_observation():
    """ISSUE 8 satellite acceptance (slow: two ~3s open-loop arms): a
    dispatch-stalled replica -- the cross-host leader failure mode, fast
    X-Kdlt-Stalled 503s with /healthz failing -- is fed at most a couple
    requests once marked out (health-aware pool), while blind round-robin
    keeps sending it its full traffic share."""
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench

    out, rc = bench.bench_chaos_ab(
        duration_s=3.0, rate_rps=20.0, device_ms=20.0,
        deadline_ms=2000.0, hedge_delay_ms=100.0, probe_interval_s=0.5,
        seed=0, mode="stall",
    )
    on = out["arms"]["failover_on"]
    off = out["arms"]["failover_off"]
    assert rc == 0, out
    assert on["post_kill_in_deadline_rate"] >= 0.95
    assert on["post_kill_victim_requests"] <= 3, (
        "the pool kept feeding the stalled replica"
    )
    assert off["post_kill_victim_requests"] >= 0.25 * off["post_kill_requests"]
