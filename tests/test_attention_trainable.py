"""attention_trainable: the custom-VJP memory-efficient attention must be
gradient-exact against autodiff through the einsum reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.ops.attention import (
    attention_trainable,
    mha_reference,
)


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).standard_normal(shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_values_and_grads_match_reference(causal):
    b, h, s, d = 2, 3, 32, 16
    q, k, v = (_rand((b, h, s, d), i) for i in range(3))
    cot = _rand((b, h, s, d), 7)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) * cot)

    out = attention_trainable(q, k, v, causal=causal)
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)

    g_new = jax.grad(loss(attention_trainable), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(mha_reference), argnums=(0, 1, 2))(q, k, v)
    for a, r, name in zip(g_new, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), atol=1e-4, err_msg=f"d{name}"
        )


def test_cross_attention_shapes(causal=False):
    # sq != sk: each side tiles independently (or falls back); grads exact.
    b, h, sq, sk, d = 1, 2, 32, 16, 8
    q = _rand((b, h, sq, d), 0)
    k = _rand((b, h, sk, d), 1)
    v = _rand((b, h, sk, d), 2)

    out = attention_trainable(q, k, v)
    want = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_new = jax.grad(loss(attention_trainable), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(mha_reference), argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(g_new, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=1e-4)


def test_untiled_sequence_falls_back_but_stays_exact():
    # S=12 has no MXU tiling (pick_block -> None): the single-block backward
    # path must still be gradient-exact.
    b, h, s, d = 1, 2, 12, 8
    q, k, v = (_rand((b, h, s, d), i) for i in range(3))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_new = jax.grad(loss(attention_trainable))(q, k, v)
    g_ref = jax.grad(loss(mha_reference))(q, k, v)
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref), atol=1e-4)


def test_jit_and_vit_train_use_it():
    # Under jit (the train-step context) and through the ViT's train path.
    b, h, s, d = 1, 2, 16, 8
    q, k, v = (_rand((b, h, s, d), i) for i in range(3))
    jitted = jax.jit(lambda q, k, v: attention_trainable(q, k, v).sum())
    assert np.isfinite(float(jitted(q, k, v)))

    grads = jax.jit(jax.grad(lambda q, k, v: attention_trainable(q, k, v).sum(),
                             argnums=(0, 1, 2)))(q, k, v)
    assert all(np.isfinite(np.asarray(g)).all() for g in grads)
