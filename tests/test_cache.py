"""Gateway content-addressed cache + singleflight coalescing (ISSUE 8).

Three layers of coverage: the cache/singleflight primitives in isolation
(serving/cache.py), the gateway wiring with stubbed fetch/upstream (hit
vs miss vs coalesced dispositions, per-waiter deadlines, hot-reload
invalidation, the KDLT_CACHE kill switch), and one real HTTP stack e2e
(stub model tier, real gateway, kdlt-client stats) proving the
subsystem's wire surface: X-Kdlt-Cache dispositions, the cache-bust salt,
/debug/cache, and the artifact-hash header round trip.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.serving import cache as cache_lib
from kubernetes_deep_learning_tpu.serving.admission import Deadline
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib


# --- content addressing ------------------------------------------------------


def test_content_key_is_deterministic_and_field_separated():
    k1 = cache_lib.content_key("m", "h", "p", "payload")
    assert k1 == cache_lib.content_key("m", "h", "p", "payload")
    assert len(k1) == 64  # sha256 hex
    # Length-prefixed fields: shifting bytes between adjacent fields must
    # not collide.
    assert cache_lib.content_key("m", "ab", "c", "x") != (
        cache_lib.content_key("m", "a", "bc", "x")
    )
    # Every canonical field participates.
    base = ("model", "hash", "params", "url")
    for i in range(4):
        other = list(base)
        other[i] = other[i] + "!"
        assert cache_lib.content_key(*other) != cache_lib.content_key(*base)
    # The salt splits identities; identical salts agree.
    assert cache_lib.content_key(*base, salt="s") != (
        cache_lib.content_key(*base)
    )
    assert cache_lib.content_key(*base, salt="s") == (
        cache_lib.content_key(*base, salt="s")
    )


def test_cache_enabled_env_gate(monkeypatch):
    monkeypatch.delenv(cache_lib.CACHE_ENV, raising=False)
    assert cache_lib.cache_enabled() is True
    for off in ("0", "false", "off", "no"):
        monkeypatch.setenv(cache_lib.CACHE_ENV, off)
        assert cache_lib.cache_enabled() is False
    monkeypatch.setenv(cache_lib.CACHE_ENV, "1")
    assert cache_lib.cache_enabled() is True
    # Explicit argument wins over the env.
    monkeypatch.setenv(cache_lib.CACHE_ENV, "0")
    assert cache_lib.cache_enabled(True) is True


# --- ResponseCache primitives ------------------------------------------------


def test_response_cache_put_get_and_ttl_expiry():
    c = cache_lib.ResponseCache(ttl_s=0.05, max_mb=1.0)
    assert c.get("k") is None
    c.put("k", b"body", "application/json", "m", "h1")
    assert c.get("k") == (b"body", "application/json")
    time.sleep(0.08)
    assert c.get("k") is None  # expired
    assert c.evictions["ttl"] == 1


def test_response_cache_lru_eviction_respects_byte_budget():
    c = cache_lib.ResponseCache(ttl_s=60.0, max_mb=1.0)
    c.max_bytes = 100  # three 40-byte bodies cannot coexist
    c.put("a", b"x" * 40, "t", "m", "h")
    c.put("b", b"x" * 40, "t", "m", "h")
    assert c.get("a") is not None  # LRU-touch: "b" is now the oldest
    c.put("c", b"x" * 40, "t", "m", "h")
    assert c.get("b") is None and c.get("a") is not None
    assert c.get("c") is not None
    assert c.evictions["lru"] == 1
    # A body larger than the whole budget is never stored.
    assert c.put("huge", b"x" * 200, "t", "m", "h") is False
    assert c.get("huge") is None


def test_event_stream_responses_are_never_storable():
    # ISSUE 17 regression: a text/event-stream body is a live token
    # stream's transcript -- caching or singleflight-fanning one would
    # replay client A's generation to client B as a dead recording.  The
    # store predicate refuses the content type outright, for every
    # otherwise-storable status, so no future route can wire a stream
    # into the cache by accident.
    c = cache_lib.ResponseCache(ttl_s=60.0, max_mb=1.0, neg_ttl_s=5.0)
    assert c.storable_response(200, "application/json") is True
    assert c.storable_response(200, "text/event-stream") is False
    # Parameters and casing do not re-admit it.
    assert c.storable_response(200, "TEXT/EVENT-STREAM; charset=utf-8") is False
    assert c.storable_response(200, " text/event-stream ") is False
    assert c.storable_response(404, "text/event-stream") is False
    # No content type (legacy callers) falls back to the status rule.
    assert c.storable_response(200, None) is True
    # put() enforces the same predicate end to end.
    assert c.put("s", b"data: {}\n\n", "text/event-stream", "m", "h") is False
    assert c.get("s") is None
    assert c.put("j", b"{}", "application/json", "m", "h") is True


def test_response_cache_artifact_hash_invalidation_semantics():
    c = cache_lib.ResponseCache(ttl_s=60.0, max_mb=1.0)
    assert c.resolved_hash("m") == cache_lib.UNRESOLVED_HASH
    c.note_artifact_hash("m", "h1")
    c.put("k1", b"one", "t", "m", "h1")
    c.put("other-model", b"two", "t", "n", "zz")
    # Same hash again (e.g. a byte-identical version bump): entries kept.
    c.note_artifact_hash("m", "h1")
    assert c.get("k1") is not None
    # Changed bytes -> changed hash: m's entries drop, other models keep.
    c.note_artifact_hash("m", "h2")
    assert c.get("k1") is None
    assert c.get("other-model") is not None
    assert c.evictions["reload"] == 1
    assert c.resolved_hash("m") == "h2"


def test_response_cache_invalidate_model_scoped_drop():
    c = cache_lib.ResponseCache(ttl_s=60.0, max_mb=1.0)
    c.put("a", b"1", "t", "m", "h")
    c.put("b", b"2", "t", "m", "h")
    c.put("c", b"3", "t", "n", "h")
    assert c.invalidate_model("m") == 2
    assert c.get("a") is None and c.get("b") is None
    assert c.get("c") is not None


def test_cache_metrics_minted_centrally_and_updated():
    reg = metrics_lib.Registry()
    c = cache_lib.ResponseCache(registry=reg, ttl_s=60.0, max_mb=1.0)
    c.put("k", b"body", "t", "m", "h")
    c.get("k")
    c.count_miss()
    c.count_coalesced()
    page = reg.render()
    assert "kdlt_cache_hits_total 1" in page
    assert "kdlt_cache_misses_total 1" in page
    assert "kdlt_cache_coalesced_total 1" in page
    assert "kdlt_cache_bytes_total 4" in page
    assert "kdlt_cache_resident_bytes 4" in page
    assert 'kdlt_cache_evictions_total{reason="lru"} 0' in page
    assert "kdlt_cache_hit_ratio 0.5" in page


# --- singleflight primitives -------------------------------------------------


def test_singleflight_leader_resolves_followers():
    sf = cache_lib.SingleFlight()
    flight, leader = sf.begin("k")
    assert leader is True
    same, again = sf.begin("k")
    assert again is False and same is flight
    results = []
    t = threading.Thread(target=lambda: results.append(same.wait(5.0)))
    t.start()
    sf.finish("k", flight)
    flight.resolve("answer")
    t.join(timeout=5)
    assert results == ["answer"]
    # After finish, the key starts a fresh flight.
    _, leader2 = sf.begin("k")
    assert leader2 is True


def test_singleflight_wait_timeout_and_failure_propagation():
    sf = cache_lib.SingleFlight()
    flight, _ = sf.begin("k")
    with pytest.raises(cache_lib.FlightTimeout):
        flight.wait(0.02)  # the waiter's own budget, leader uncancelled
    flight.fail(RuntimeError("leader died"))
    with pytest.raises(RuntimeError, match="leader died"):
        flight.wait(1.0)


def test_singleflight_finish_is_identity_checked():
    sf = cache_lib.SingleFlight()
    flight, _ = sf.begin("k")
    sf.finish("k", flight)
    replacement, leader = sf.begin("k")
    assert leader is True
    sf.finish("k", flight)  # stale leader must not evict the replacement
    joined, leader2 = sf.begin("k")
    assert leader2 is False and joined is replacement


# --- gateway wiring (stubbed fetch + upstream) -------------------------------


def _stub_gateway(monkeypatch=None, upstream_delay_s=0.0, **kw):
    """A bind=False Gateway whose fetch and upstream hop are stubbed; the
    upstream call count is the singleflight/caching ground truth."""
    gw = Gateway(
        serving_host="127.0.0.1:1", model="stub-model", bind=False, **kw
    )
    calls = {"n": 0}

    def fake_fetch(url):
        return np.zeros((8, 8, 3), np.uint8)

    def fake_predict_batch(images, request_id="", deadline=None, trace=None,
                           model=None, priority=None):
        calls["n"] += 1
        if upstream_delay_s:
            time.sleep(upstream_delay_s)
        if gw.cache is not None:
            gw.cache.note_artifact_hash(model or gw.model, "hash-v1")
        return [np.arange(3, dtype=np.float32)], ["a", "b", "c"]

    gw._fetch_one = fake_fetch
    gw._predict_batch = fake_predict_batch
    return gw, calls


def test_gateway_hit_skips_upstream_and_admission_slot():
    gw, calls = _stub_gateway()
    try:
        body = json.dumps({"url": "http://img/x.png"}).encode()
        s1, out1, _, h1 = gw.handle_predict(body, "rid-1")
        s2, out2, _, h2 = gw.handle_predict(body, "rid-2")
        assert (s1, s2) == (200, 200)
        assert h1[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert h2[cache_lib.CACHE_STATUS_HEADER] == "hit"
        assert out1 == out2
        assert calls["n"] == 1
        # The hit consumed no admission slot: exactly one request (the
        # miss) was seen/admitted by the controller.
        assert gw.admission._m["requests"].value == 1
        assert gw.admission._m["admitted"].value == 1
        # Both requests landed in the latency/SLO boundary.
        assert gw._m_latency.count == 2
        # The hit's trace carries the gateway.cache span.
        spans = gw.tracer.spans("rid-2")
        cache_span = next(s for s in spans if s["name"] == "gateway.cache")
        assert cache_span["tags"]["result"] == "hit"
    finally:
        gw.shutdown()


def test_gateway_kill_switch_disables_cache_and_coalescing(monkeypatch):
    monkeypatch.setenv(cache_lib.CACHE_ENV, "0")
    gw, calls = _stub_gateway()
    try:
        assert gw.cache is None
        body = json.dumps({"url": "http://img/x.png"}).encode()
        _, _, _, h1 = gw.handle_predict(body, "rid-1")
        _, _, _, h2 = gw.handle_predict(body, "rid-2")
        assert cache_lib.CACHE_STATUS_HEADER not in h1
        assert cache_lib.CACHE_STATUS_HEADER not in h2
        assert calls["n"] == 2  # the legacy path, exactly
    finally:
        gw.shutdown()


def test_gateway_batch_requests_bypass_the_cache():
    gw, calls = _stub_gateway()
    try:
        body = json.dumps({"urls": ["http://img/x.png"]}).encode()
        gw.pool.reference_spec = None  # spec_for is stubbed below
        gw.spec_for = lambda model=None: None
        s1, _, _, h1 = gw.handle_predict(body, "rid-1")
        s2, _, _, h2 = gw.handle_predict(body, "rid-2")
        assert (s1, s2) == (200, 200)
        assert cache_lib.CACHE_STATUS_HEADER not in h1
        assert cache_lib.CACHE_STATUS_HEADER not in h2
        assert calls["n"] == 2
    finally:
        gw.shutdown()


def test_gateway_cache_bust_salt_coalesces_but_never_stores():
    gw, calls = _stub_gateway()
    try:
        body = json.dumps({"url": "http://img/x.png"}).encode()
        _, _, _, h1 = gw.handle_predict(body, "rid-1", cache_bust="salt-a")
        _, _, _, h2 = gw.handle_predict(body, "rid-2", cache_bust="salt-a")
        # Sequential identical salted requests: both full misses -- the
        # salt opts out of storage (identical CONCURRENT salted requests
        # would still coalesce via singleflight).
        assert h1[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert h2[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert calls["n"] == 2
        assert gw.cache.stats()["entries"] == 0
        # And the unsalted request is independent of the salted ones.
        _, _, _, h3 = gw.handle_predict(body, "rid-3")
        assert h3[cache_lib.CACHE_STATUS_HEADER] == "miss"
        _, _, _, h4 = gw.handle_predict(body, "rid-4")
        assert h4[cache_lib.CACHE_STATUS_HEADER] == "hit"
    finally:
        gw.shutdown()


def test_hung_flight_waiters_honor_their_own_deadlines():
    """ISSUE 8 satellite: a follower whose budget expires gets its OWN 504
    without cancelling the leader, whose flight completes and is cached."""
    gw, calls = _stub_gateway(upstream_delay_s=1.0)
    try:
        body = json.dumps({"url": "http://img/slow.png"}).encode()
        leader_result: dict = {}

        def lead():
            leader_result["resp"] = gw.handle_predict(
                body, "rid-leader", Deadline(10.0)
            )

        t = threading.Thread(target=lead, daemon=True)
        t.start()
        deadline_t0 = time.monotonic()
        while not gw._singleflight.stats()["inflight_flights"]:
            assert time.monotonic() - deadline_t0 < 5.0, "leader never took off"
            time.sleep(0.005)
        w0 = time.monotonic()
        status, out, _, headers = gw.handle_predict(
            body, "rid-follower", Deadline(0.15)
        )
        follower_wait = time.monotonic() - w0
        assert status == 504
        assert headers[cache_lib.CACHE_STATUS_HEADER] == "coalesced"
        assert "coalesced" in json.loads(out)["error"]
        assert follower_wait < 0.8  # its own budget, not the leader's 1s
        t.join(timeout=5)
        assert leader_result["resp"][0] == 200  # the leader was NOT cancelled
        assert calls["n"] == 1
        # The leader's answer was cached despite the follower's 504.
        status, _, _, headers = gw.handle_predict(body, "rid-after")
        assert status == 200
        assert headers[cache_lib.CACHE_STATUS_HEADER] == "hit"
    finally:
        gw.shutdown()


def test_concurrent_identical_requests_coalesce_to_one_upstream_call():
    gw, calls = _stub_gateway(upstream_delay_s=0.25)
    try:
        body = json.dumps({"url": "http://img/popular.png"}).encode()
        results: list = []

        def fire(i):
            results.append(gw.handle_predict(body, f"rid-{i}", Deadline(10.0)))

        threads = [
            threading.Thread(target=fire, args=(i,), daemon=True)
            for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert len(results) == 8
        assert all(r[0] == 200 for r in results)
        assert all(
            json.loads(r[1].decode()) == json.loads(results[0][1].decode())
            for r in results
        )
        assert calls["n"] == 1, "singleflight must collapse to ONE dispatch"
        stats = gw.cache.stats()
        assert stats["misses"] == 1 and stats["coalesced"] == 7
        # Followers are admitted-but-not-dispatched: the admission counters
        # saw all 8, the limiter slots only the leader.
        assert gw.admission._m["requests"].value == 8
        assert gw.admission._m["admitted"].value == 8
    finally:
        gw.shutdown()


def test_upstream_error_is_shared_with_followers_but_never_cached():
    """ISSUE 8 satellite (cache x faults): a failed flight's error fans
    out to its waiters, but the NEXT request retries upstream -- errors
    must never be served from the cache."""
    gw, calls = _stub_gateway()
    fail = {"on": True}
    real_predict = gw._predict_batch

    def flaky(images, request_id="", deadline=None, trace=None, model=None,
              priority=None):
        if fail["on"]:
            calls["n"] += 1
            from kubernetes_deep_learning_tpu.serving.gateway import (
                UpstreamError,
            )

            raise UpstreamError("injected model tier failure", 502)
        return real_predict(images, request_id, deadline, trace, model,
                            priority=priority)

    gw._predict_batch = flaky
    try:
        body = json.dumps({"url": "http://img/flaky.png"}).encode()
        s1, out1, _, h1 = gw.handle_predict(body, "rid-1")
        assert s1 == 502 and "injected" in json.loads(out1)["error"]
        assert h1[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert gw.cache.stats()["entries"] == 0  # the 502 was NOT cached
        fail["on"] = False
        s2, _, _, h2 = gw.handle_predict(body, "rid-2")
        assert s2 == 200  # a real retry, not a cached error
        assert h2[cache_lib.CACHE_STATUS_HEADER] == "miss"
        s3, _, _, h3 = gw.handle_predict(body, "rid-3")
        assert s3 == 200 and h3[cache_lib.CACHE_STATUS_HEADER] == "hit"
    finally:
        gw.shutdown()


def test_hot_reload_with_changed_bytes_evicts_cached_entries():
    """ISSUE 8 satellite: the artifact hash is the invalidation key -- a
    reload with changed bytes drops the model's entries; a byte-identical
    version bump (same hash) keeps them."""
    gw, calls = _stub_gateway()
    current = {"hash": "artifact-v1"}
    real_predict = gw._predict_batch

    def versioned(images, request_id="", deadline=None, trace=None,
                  model=None, priority=None):
        calls["n"] += 1
        gw.cache.note_artifact_hash(model or gw.model, current["hash"])
        return [np.arange(3, dtype=np.float32)], ["a", "b", "c"]

    del real_predict
    gw._predict_batch = versioned
    try:
        body = json.dumps({"url": "http://img/x.png"}).encode()
        gw.handle_predict(body, "rid-1")
        _, _, _, h = gw.handle_predict(body, "rid-2")
        assert h[cache_lib.CACHE_STATUS_HEADER] == "hit"
        assert calls["n"] == 1
        # Byte-identical re-export under a higher version: same hash ->
        # entries kept (note arrives via some other model's response).
        gw.cache.note_artifact_hash(gw.model, "artifact-v1")
        _, _, _, h = gw.handle_predict(body, "rid-3")
        assert h[cache_lib.CACHE_STATUS_HEADER] == "hit"
        # Hot reload with CHANGED bytes: the hash changes, entries drop,
        # and the next request re-dispatches upstream.
        current["hash"] = "artifact-v2"
        gw.cache.note_artifact_hash(gw.model, "artifact-v2")
        _, _, _, h = gw.handle_predict(body, "rid-4")
        assert h[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert calls["n"] == 2
        assert gw.cache.stats()["evictions"]["reload"] >= 1
        # And the re-primed entry serves hits under the new hash.
        _, _, _, h = gw.handle_predict(body, "rid-5")
        assert h[cache_lib.CACHE_STATUS_HEADER] == "hit"
    finally:
        gw.shutdown()


def test_debug_cache_endpoint_payload():
    gw, _calls = _stub_gateway()
    try:
        body = json.dumps({"url": "http://img/x.png"}).encode()
        gw.handle_predict(body, "rid-1")
        gw.handle_predict(body, "rid-2")
        status, payload, ctype = gw.handle_get("/debug/cache")
        assert status == 200 and ctype == "application/json"
        data = json.loads(payload)
        assert data["enabled"] is True
        assert data["entries"] == 1
        assert data["hits"] == 1 and data["misses"] == 1
        assert data["hit_ratio"] == 0.5
        assert data["entries_by_model"] == {gw.model: 1}
        assert data["artifact_hashes"] == {gw.model: "hash-v1"}
        assert data["inflight_flights"] == 0
        assert data["resident_bytes"] == data["max_bytes"] or (
            data["resident_bytes"] <= data["max_bytes"]
        )
    finally:
        gw.shutdown()


def test_debug_cache_reports_disabled_posture(monkeypatch):
    monkeypatch.setenv(cache_lib.CACHE_ENV, "0")
    gw, _calls = _stub_gateway()
    try:
        status, payload, _ = gw.handle_get("/debug/cache")
        assert status == 200
        assert json.loads(payload) == {"enabled": False}
    finally:
        gw.shutdown()


# --- real HTTP stack: wire surface + kdlt-client stats ----------------------


def test_e2e_client_sees_cache_dispositions_and_bust(tmp_path):
    """One real stack (stub model tier, real gateway, HTTP): the client's
    stats['cache'] column (ISSUE 8 satellite), the cache-bust salt, the
    artifact-hash header round trip into /debug/cache, and the
    singleflight counter on /metrics."""
    import os as _os
    import threading as _threading
    from functools import partial
    from http.server import HTTPServer, SimpleHTTPRequestHandler

    import requests
    from PIL import Image

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
    from kubernetes_deep_learning_tpu.serving.client import predict_url
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    spec = register_spec(
        ModelSpec(
            name="cache-e2e",
            family="xception",  # never instantiated by StubEngine
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )
    root = tmp_path / "models"
    art.save_artifact(
        art.version_dir(str(root), spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        str(root), port=0, buckets=(1, 2), max_delay_ms=1.0, host="127.0.0.1",
        engine_factory=StubEngine,
    )
    server.warmup()
    server.start()
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name,
        port=0, host="127.0.0.1",
    )
    gw.start()

    class Quiet(SimpleHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

    img_dir = tmp_path / "img"
    img_dir.mkdir()
    rng = np.random.default_rng(0)
    Image.fromarray(
        rng.integers(0, 256, size=(48, 48, 3), dtype=np.uint8)
    ).save(_os.path.join(str(img_dir), "img.png"))
    httpd = HTTPServer(
        ("127.0.0.1", 0), partial(Quiet, directory=str(img_dir))
    )
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    img_url = f"http://127.0.0.1:{httpd.server_address[1]}/img.png"
    base = f"http://127.0.0.1:{gw.port}"
    try:
        stats: dict = {}
        first = predict_url(base, img_url, stats=stats)
        assert stats["cache"] == "miss"
        stats = {}
        second = predict_url(base, img_url, stats=stats)
        assert stats["cache"] == "hit"
        assert first == second
        # --cache-bust semantics: a salted request bypasses the cached
        # answer but computes the same scores.
        stats = {}
        busted = predict_url(base, img_url, stats=stats, cache_bust="salt-1")
        assert stats["cache"] == "miss"
        assert busted == second
        # The model tier's artifact hash round-tripped into the cache.
        dbg = requests.get(f"{base}/debug/cache", timeout=5).json()
        served = list(server.models.values())[0]
        assert dbg["artifact_hashes"][spec.name] == served.artifact_hash
        assert dbg["hits"] == 1
        # The cache series render on /metrics (strict exposition is
        # covered by test_exposition; here: the counters moved).
        page = requests.get(f"{base}/metrics", timeout=5).text
        assert "kdlt_cache_hits_total 1" in page
    finally:
        gw.shutdown()
        server.shutdown()
        httpd.shutdown()


# --- negative caching (ISSUE 9 satellite / ROADMAP cache follow-on #1) ------


def test_negative_cache_put_lookup_expiry_and_5xx_refusal():
    c = cache_lib.ResponseCache(ttl_s=60.0, max_mb=1.0, neg_ttl_s=0.05)
    # 404/400 are storable under the negative TTL; 5xx never.
    assert c.storable_status(200) and c.storable_status(404)
    assert c.storable_status(400)
    for status in (500, 502, 503, 504):
        assert not c.storable_status(status)
        assert c.put("k5", b"boom", "t", "m", "h", status=status) is False
    assert c.lookup("k5") is None
    # A stored 404 answers with ITS status and counts as a negative hit.
    assert c.put("k", b'{"error":"no"}', "application/json", "m", "h",
                 status=404) is True
    assert c.lookup("k") == (404, b'{"error":"no"}', "application/json")
    assert c.negative_hits == 1 and c.hits == 1
    assert c.stats()["negative_entries"] == 1
    assert c.stats()["negative_hits"] == 1
    # ...and expires on the SHORT ttl, not the positive one.
    time.sleep(0.06)
    assert c.lookup("k") is None
    assert c.evictions["ttl"] == 1
    # A positive entry under the same clock survives (ttl_s=60).
    c.put("pos", b"ok", "t", "m", "h")
    time.sleep(0.06)
    assert c.lookup("pos") == (200, b"ok", "t")


def test_negative_cache_disabled_when_ttl_zero():
    c = cache_lib.ResponseCache(ttl_s=60.0, max_mb=1.0, neg_ttl_s=0.0)
    assert not c.storable_status(404)
    assert c.put("k", b"x", "t", "m", "h", status=404) is False
    # 200s still cache normally.
    assert c.put("k", b"x", "t", "m", "h") is True


def test_negative_cache_metrics_minted_centrally():
    reg = metrics_lib.Registry()
    c = cache_lib.ResponseCache(registry=reg, ttl_s=60.0, max_mb=1.0,
                                neg_ttl_s=5.0)
    c.put("k", b"e", "t", "m", "h", status=400)
    c.lookup("k")
    page = reg.render()
    assert "kdlt_cache_negative_hits_total 1" in page


def _failing_fetch_gateway(neg_ttl_s, fail_with=None, **kw):
    """A stub gateway whose image fetch always fails (the hammered-bad-URL
    scenario); ``fetches`` is the cost ground truth."""
    from kubernetes_deep_learning_tpu.serving.gateway import UpstreamError

    gw = Gateway(
        serving_host="127.0.0.1:1", model="stub-model", bind=False,
        cache_neg_ttl_s=neg_ttl_s, **kw
    )
    fetches = {"n": 0}

    def fake_fetch(url):
        fetches["n"] += 1
        if fail_with is not None:
            raise fail_with
        raise ValueError("404 Not Found fetching image")

    gw._fetch_one = fake_fetch
    return gw, fetches


def test_gateway_negative_caches_repeated_bad_url():
    gw, fetches = _failing_fetch_gateway(neg_ttl_s=5.0)
    try:
        body = json.dumps({"url": "http://img/broken.png"}).encode()
        s1, out1, _, h1 = gw.handle_predict(body, "rid-1")
        assert s1 == 400
        assert h1[cache_lib.CACHE_STATUS_HEADER] == "miss"
        s2, out2, _, h2 = gw.handle_predict(body, "rid-2")
        assert s2 == 400 and out2 == out1
        assert h2[cache_lib.CACHE_STATUS_HEADER] == "hit"
        assert fetches["n"] == 1  # the hammered bad URL paid the path ONCE
        assert gw.cache.negative_hits == 1
        # A different URL is its own identity.
        s3, _, _, h3 = gw.handle_predict(
            json.dumps({"url": "http://img/other.png"}).encode(), "rid-3"
        )
        assert s3 == 400 and h3[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert fetches["n"] == 2
    finally:
        gw.shutdown()


def test_gateway_negative_cache_expires_and_disabled_posture():
    gw, fetches = _failing_fetch_gateway(neg_ttl_s=0.05)
    try:
        body = json.dumps({"url": "http://img/broken.png"}).encode()
        gw.handle_predict(body, "rid-1")
        time.sleep(0.06)
        _, _, _, h2 = gw.handle_predict(body, "rid-2")
        assert h2[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert fetches["n"] == 2  # expired: the bad URL is re-checked
    finally:
        gw.shutdown()
    gw, fetches = _failing_fetch_gateway(neg_ttl_s=0.0)
    try:
        body = json.dumps({"url": "http://img/broken.png"}).encode()
        gw.handle_predict(body, "rid-1")
        _, _, _, h2 = gw.handle_predict(body, "rid-2")
        assert h2[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert fetches["n"] == 2  # negative caching off: full path per hit
    finally:
        gw.shutdown()


def test_gateway_never_negative_caches_5xx():
    from kubernetes_deep_learning_tpu.serving.gateway import UpstreamError

    gw, fetches = _failing_fetch_gateway(
        neg_ttl_s=5.0, fail_with=UpstreamError("replica down", http_status=502)
    )
    try:
        body = json.dumps({"url": "http://img/x.png"}).encode()
        s1, _, _, _ = gw.handle_predict(body, "rid-1")
        s2, _, _, h2 = gw.handle_predict(body, "rid-2")
        assert (s1, s2) == (502, 502)
        assert h2[cache_lib.CACHE_STATUS_HEADER] == "miss"
        assert fetches["n"] == 2  # a transient upstream failure is never replayed
        assert gw.cache.stats()["entries"] == 0
    finally:
        gw.shutdown()
