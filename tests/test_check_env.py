"""tools/check_env.py wired into tier-1: every KDLT_* knob the tree
reads must be documented in GUIDE.md, deploy manifest keys must exist in
code, and the compose/k8s mirrors of each tier must agree -- plus unit
coverage that the lint's own pieces catch what they claim to."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

import check_env  # noqa: E402


def test_production_tree_is_clean(capsys):
    assert check_env.main() == 0, capsys.readouterr().out


def test_env_literals_whole_string_only():
    # Whole-string KDLT_* literals are env names; WSGI keys and doc
    # fragments embedding the pattern are not.
    found = check_env.env_literals(
        'A = "KDLT_FOO"\n'
        'B = os.environ.get("KDLT_BAR_S", "1")\n'
        'W = "HTTP_X_KDLT_PRIORITY"\n'   # WSGI key, not an env var
        'D = "see $KDLT_DOCS for why"\n',  # prose, not a name
        "fake.py",
    )
    assert set(found) == {"KDLT_FOO", "KDLT_BAR_S"}
    assert found["KDLT_FOO"] == 1


def test_compose_env_parses_map_and_list_forms():
    doc = {"services": {
        "a": {"environment": {"KDLT_X": 1, "OTHER": "y"}},
        "b": {"environment": ["KDLT_Y=2", "PATH=/x"]},
    }}
    assert check_env.compose_env(doc, "a") == {"KDLT_X": "1"}
    assert check_env.compose_env(doc, "b") == {"KDLT_Y": "2"}
    assert check_env.compose_env(doc, "missing") == {}


def test_k8s_env_walks_all_containers():
    doc = {"spec": {"template": {"spec": {"containers": [
        {"env": [{"name": "KDLT_X", "value": "1"},
                 {"name": "POD_IP", "value": "x"}]},
        {"env": [{"name": "KDLT_Y", "value": "2"}]},
    ]}}}}
    assert check_env.k8s_env(doc) == {"KDLT_X": "1", "KDLT_Y": "2"}


def test_new_isolation_knobs_are_wired_everywhere():
    # The ISSUE-12 knobs must be present (and equal) in both deploy
    # mirrors of the tier that owns them -- presence here, agreement via
    # main() above.
    import yaml

    with open(os.path.join(check_env.REPO, check_env.COMPOSE)) as f:
        compose = yaml.safe_load(f)
    with open(os.path.join(check_env.REPO, check_env.K8S_GATEWAY)) as f:
        k8s_gw = check_env.k8s_env(yaml.safe_load(f))
    with open(os.path.join(check_env.REPO, check_env.K8S_MODEL)) as f:
        k8s_model = check_env.k8s_env(yaml.safe_load(f))
    gw = check_env.compose_env(compose, "gateway")
    for knob in ("KDLT_ADMIT_BUDGETS", "KDLT_BROWNOUT",
                 "KDLT_BROWNOUT_BURN_ENTER", "KDLT_BROWNOUT_BURN_EXIT",
                 "KDLT_CACHE_SWR_S"):
        assert knob in gw, knob
        assert knob in k8s_gw, knob
        assert gw[knob] == k8s_gw[knob], knob
    for svc in ("model-server", "model-server-b"):
        env = check_env.compose_env(compose, svc)
        assert env["KDLT_ADMIT_BUDGETS"] == k8s_model["KDLT_ADMIT_BUDGETS"]


def test_every_knob_in_guide_is_spelled_in_full(tmp_path):
    # The failure mode the lint exists for: a knob read by code but
    # absent from GUIDE.md (e.g. hidden inside a brace-expansion like
    # KDLT_X_{MIN,MAX}) must be flagged.  Simulate by checking the
    # production scan's names against a guide stripped of one of them.
    code_envs = {}
    for path in check_env.iter_production_files():
        with open(path) as f:
            code_envs.update(check_env.env_literals(f.read(), path))
    assert "KDLT_ADMISSION_MAX_CONCURRENCY" in code_envs
    assert "KDLT_BROWNOUT_DWELL_S" in code_envs
    with open(os.path.join(check_env.REPO, check_env.GUIDE)) as f:
        guide = f.read()
    for name in code_envs:
        assert name in guide, f"{name} undocumented in GUIDE.md"
