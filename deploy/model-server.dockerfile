# TPU model-server image: the in-tree replacement for the reference's
# tf-serving.dockerfile (tensorflow/serving:2.3.0 + baked-in SavedModel,
# reference tf-serving.dockerfile:1-5).  Same pattern: base runtime, bake the
# versioned model artifact into /models, select the model via env.
#
# Build (repo root):
#   docker build -t kdlt-model-server -f deploy/model-server.dockerfile .
# The artifact is produced beforehand with:
#   kdlt-export --model clothing-model --weights xception_v4.h5 --output ./models
# MULTI-MODEL: export any further models into the same root before the build
# (e.g. `kdlt-export --model vit --output ./models`); the server's registry
# scans /models and serves every <name>/<version>/ it finds from one process,
# with the unified scheduler (KDLT_SCHED_POLICY/KDLT_SCHED_WEIGHTS, GUIDE 10h)
# arbitrating their shared device time.  Route via /predict/<model> at the
# gateway or /v1/models/<name>:predict here.
#
# GPU-vs-CPU in the reference is a one-line image swap (tf-serving.dockerfile:1);
# here TPU-vs-CPU is one pip extra: jax[tpu] resolves the TPU PJRT plugin on a
# GKE TPU node, and the identical image falls back to CPU off-TPU (the exported
# StableHLO is lowered for both platforms, export/exporter.py DEFAULT_PLATFORMS).

FROM python:3.11-slim

ENV PYTHONUNBUFFERED=TRUE

# Constrained from the very first resolve: an unpinned jax[tpu] here would
# pull a libtpu matched to a NEWER jaxlib than the pinned one installed
# below, and the stale PJRT plugin fails at runtime on the TPU node.
COPY requirements.lock /tmp/requirements.lock
RUN pip install --no-cache-dir -c /tmp/requirements.lock "jax[tpu]" \
      -f https://storage.googleapis.com/jax-releases/libtpu_releases.html || \
    pip install --no-cache-dir -c /tmp/requirements.lock jax

WORKDIR /app
COPY pyproject.toml requirements.lock ./
COPY kubernetes_deep_learning_tpu ./kubernetes_deep_learning_tpu
# requirements.lock pins the full transitive closure (the reference's
# Pipfile.lock role).
RUN pip install --no-cache-dir -c requirements.lock ".[grpc]"

# Versioned artifact layout /models/<name>/<version>/ -- the same convention
# the reference bakes its SavedModel with (tf-serving.dockerfile:5).
COPY models /models

# Bake a hot XLA compile cache into the image layer (zero-cold-start
# scale-up, GUIDE 10k): AOT-compile every baked model's full bucket ladder
# NOW so each pod this image ever starts warms from disk -- cache hits in
# seconds, exactly when the HPA added the pod because load spiked.  Cache
# keys include the target platform and the build host has no TPU, so this
# bakes the cpu programs; TPU pods pre-fill their shared cache volume at
# init instead (KDLT_AOT_WARM=1, model-server-deployment.yaml).  Fail-soft:
# a warm failure costs cold-start time, never the image build.
RUN kdlt-warm --models /models --compile-cache-dir /var/cache/kdlt-xla --platform cpu || \
    echo "kdlt-warm: bake failed; pods will compile at first warmup" >&2

# 8500 = msgpack/JSON HTTP (probes, gateway); 8501 = the reference's
# exact gRPC PredictionService wire (serving/grpc_predict.py) so
# TF-Serving-era clients work against this tier unmodified.
EXPOSE 8500 8501
ENTRYPOINT ["kdlt-model-server", "--models", "/models", "--port", "8500", "--grpc-port", "8501"]
