# Serving-gateway image: the reference's gateway.dockerfile equivalent
# (python:3.7.5-slim + pipenv + gunicorn, reference gateway.dockerfile:1-16).
#
# Build (repo root):
#   docker build -t kdlt-gateway -f deploy/gateway.dockerfile .
#
# Differences, deliberate: dependency pinning is pyproject-based instead of
# Pipfile; the server is the in-tree threaded gateway (stdlib, one process,
# pooled upstream connections) instead of gunicorn sync workers -- the gateway
# is pure IO, so threads beat pre-fork here (no GIL-bound compute; each worker
# process would otherwise hold its own upstream connection pool).  The gateway
# never imports jax: image stays small and boots instantly.

FROM python:3.11-slim

ENV PYTHONUNBUFFERED=TRUE

WORKDIR /app
COPY pyproject.toml requirements.lock ./
COPY kubernetes_deep_learning_tpu ./kubernetes_deep_learning_tpu
# requirements.lock pins the full transitive closure (the reference's
# Pipfile.lock role).
# .[serve] adds gunicorn so either entrypoint below works.
RUN pip install --no-cache-dir -c requirements.lock ".[serve]"

EXPOSE 9696
# Model-tier discovery via KDLT_SERVING_HOST (k8s DNS), localhost fallback for
# docker-compose style local runs -- the reference's TF_SERVING_HOST pattern
# (reference model_server.py:13, serving-gateway-deployment.yaml:22-24).
ENTRYPOINT ["kdlt-gateway", "--port", "9696"]
# gunicorn posture (the reference's exact production server,
# gateway.dockerfile:16) is available instead via serving/wsgi.py:
#   ENTRYPOINT ["gunicorn", "-w", "4", "-b", "0.0.0.0:9696", \
#               "kubernetes_deep_learning_tpu.serving.wsgi:app"]
