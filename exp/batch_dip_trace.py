"""Where does the batch-32/48 throughput dip come from?

The round-4 sweep (BENCH.md) shows per-image device time of the FUSED
serving path is non-monotonic in batch: 0.205 ms/img at batch 16 but
0.257 at 32 and 0.254 at 48, recovering to 0.226 at 64 and 0.215 at 128.
This probe traces the fast forward at several batches and aggregates
device-stream op durations by name, printing a side-by-side per-op table
(ms and ms-per-16-image-tile) so the non-scaling region is attributable
to a specific op family (entry-flow XLA fusions vs fused Pallas calls vs
transposes/head).

Usage: python exp/batch_dip_trace.py --batches 16 32 48 64 [--top 14]
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import re
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def trace_batch(batch: int, iters: int, model: str = "clothing-model"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec(model)
    dev = jax.devices()[0]
    variables = jax.device_put(init_variables(spec, seed=0), dev)
    fwd = jax.jit(build_forward(spec, dtype=jnp.bfloat16, fast="auto"))
    rng = np.random.default_rng(0)
    x = jax.device_put(
        rng.integers(0, 256, (batch, *spec.input_shape), np.uint8), dev
    )
    jax.block_until_ready(fwd(variables, x))  # compile

    trace_dir = tempfile.mkdtemp(prefix=f"kdlt-dip-{batch}-")
    with jax.profiler.trace(trace_dir):
        for _ in range(iters):
            jax.block_until_ready(fwd(variables, x))

    files = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    assert files, f"no trace files under {trace_dir}"
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)

    pids = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids[ev["pid"]] = ev["args"].get("name", "")
    device_pids = {
        pid for pid, name in pids.items() if name.startswith("/device:TPU")
    }
    agg: dict[str, float] = defaultdict(float)
    details: dict[str, str] = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") != "X" or ev.get("pid") not in device_pids:
            continue
        name = ev.get("name", "?")
        if name.startswith("jit_"):
            continue
        # Collapse instance suffixes (fusion.123 -> fusion) lightly: keep
        # the numbered name (distinct ops) but strip duplicate-run suffixes.
        agg[name] += ev.get("dur", 0) / 1e3 / iters  # -> ms/iter
        a = ev.get("args") or {}
        d = a.get("long_name") or a.get("hlo_op") or a.get("tf_op") or ""
        if d:  # don't pin "" from an argless first event
            details.setdefault(name, d)
    return dict(agg), details


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batches", type=int, nargs="+", default=[16, 32, 48, 64])
    p.add_argument("--iters", type=int, default=6)
    p.add_argument("--top", type=int, default=16)
    p.add_argument("--model", default="clothing-model")
    args = p.parse_args()

    per_batch: dict[int, dict[str, float]] = {}
    per_batch_details: dict[int, dict[str, str]] = {}
    for b in args.batches:
        per_batch[b], per_batch_details[b] = trace_batch(b, args.iters, args.model)
        total = sum(per_batch[b].values())
        print(
            f"batch {b:4d}: total {total:7.2f} ms/iter, "
            f"{total / b * 1000:6.1f} us/img"
        )

    # Rank ops by their time at the LARGEST traced batch, show all batches.
    big = max(args.batches)
    names = sorted(per_batch[big], key=lambda n: -per_batch[big][n])[: args.top]
    hdr = "op".ljust(34) + "".join(f"  b{b:<4d} (us/img)" for b in args.batches)
    print("\n" + hdr)
    # Detail strings come from the ranked (largest) batch's own program:
    # op names like fusion.123 are per-compile identities and must not be
    # annotated from a different batch size's trace.
    details = per_batch_details[big]
    for n in names:
        row = n[:33].ljust(34)
        for b in args.batches:
            ms = per_batch[b].get(n, 0.0)
            row += f"  {ms:6.2f} ({ms / b * 1000:5.1f})"
        d = details.get(n, "")
        print(row + ("   " + d[:90] if d else ""))

    # Bucket into families for the summary.  NB: "conv" must not be a bare
    # prefix test -- XLA names elementwise-cast fusions "CONVert_*_fusion",
    # which a "conv" prefix match silently books under convolution (this
    # inflated the B3 convolution row by ~5x before round 5; the SE-pool
    # convert_reduce_fusions are reduce/fusion family, not convs).
    fam_of = lambda n: (  # noqa: E731
        "pallas-fused" if "custom-call" in n or "tpu_custom_call" in n
        else "reduce-fusion" if n.startswith(("convert_reduce_fusion", "reduce"))
        else "convolution" if n.startswith(("convolution", "conv"))
        and not n.startswith("convert")
        else "fusion" if n.startswith(("fusion", "loop_fusion", "input_fusion"))
        or n.startswith(("convert", "add_convert"))
        else "copy/transpose" if re.match(r"(copy|transpose|bitcast)", n)
        else "other"
    )
    print("\nfamily summary (ms/iter):")
    fams = sorted({fam_of(n) for m in per_batch.values() for n in m})
    print("family".ljust(16) + "".join(f"  b{b:<8d}" for b in args.batches))
    for f in fams:
        row = f.ljust(16)
        for b in args.batches:
            tot = sum(ms for n, ms in per_batch[b].items() if fam_of(n) == f)
            row += f"  {tot:8.2f}"
        print(row)


if __name__ == "__main__":
    main()
