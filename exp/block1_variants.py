"""Why is the Xception stem 31% of forward time, and which rewrite fixes it?

Times block1 (normalize + conv 3x3/2 s2 -> 32ch + BN/relu + conv 3x3 -> 64ch
+ BN/relu) as written, then mathematically equivalent TPU-friendlier forms:

- s2d:    space-to-depth(2) input (150,150,12) + 2x2 conv == conv1 3x3/2.
          C_in 12 instead of 3 fills MXU lanes 4x better.
- im2col: extract 3x3 patches -> (B*149*149, 27) @ (27, 32) matmul.
- both stem convs via s2d/im2col combined.

Each variant is checked numerically against the reference formulation before
timing (atol on bf16).  Timing uses the bench.py anti-LICM chained scan.
"""

from __future__ import annotations

import argparse
import time
from functools import partial

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--scan-len", type=int, default=8)
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    dev = jax.devices()[0]
    print(f"device: {dev}, batch {args.batch}")
    rng = np.random.default_rng(0)

    # Standalone stem weights (drawn once, shared by all variants).
    k1 = rng.normal(0, 0.1, (3, 3, 3, 32)).astype(np.float32)
    s1 = rng.uniform(0.5, 1.5, 32).astype(np.float32)   # folded BN scale
    b1 = rng.normal(0, 0.1, 32).astype(np.float32)      # folded BN shift
    k2 = rng.normal(0, 0.05, (3, 3, 32, 64)).astype(np.float32)
    s2 = rng.uniform(0.5, 1.5, 64).astype(np.float32)
    b2 = rng.normal(0, 0.1, 64).astype(np.float32)
    W = {
        "k1": jnp.asarray(k1), "s1": jnp.asarray(s1), "b1": jnp.asarray(b1),
        "k2": jnp.asarray(k2), "s2": jnp.asarray(s2), "b2": jnp.asarray(b2),
    }

    def conv(x, k, stride):
        return jax.lax.conv_general_dilated(
            x, k.astype(x.dtype), (stride, stride), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32,
        )

    def stem_ref(w, img):
        x = normalize(img, "tf").astype(jnp.bfloat16)
        x = conv(x, w["k1"], 2)
        x = jnp.maximum(x * w["s1"] + w["b1"], 0.0).astype(jnp.bfloat16)
        x = conv(x, w["k2"], 1)
        x = jnp.maximum(x * w["s2"] + w["b2"], 0.0).astype(jnp.bfloat16)
        return x

    # --- variant: space-to-depth stem conv1 -------------------------------
    # k1 (3,3,3,32) -> k1s (2,2,12,32): s2d cell (di,dj) holds original pixel
    # (2i+di, 2j+dj); kernel tap (p,q) with p=2a+da reads cell (i+a) offset da.
    k1s = np.zeros((2, 2, 2, 2, 3, 32), np.float32)  # (a, da, b, db, cin, cout)
    for pp in range(3):
        for qq in range(3):
            a, da = divmod(pp, 2)
            b_, db = divmod(qq, 2)
            k1s[a, da, b_, db] = k1[pp, qq]
    # s2d channel layout: (di, dj, c) fastest-varying c  -> index di*6+dj*3+c
    k1s = k1s.transpose(0, 2, 1, 3, 4, 5).reshape(2, 2, 12, 32)
    Ws = dict(W, k1s=jnp.asarray(k1s))

    def s2d(x):
        # (B, 299, 299, 3) -> pad to 300 -> (B, 150, 150, 12)
        B = x.shape[0]
        x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
        x = x.reshape(B, 150, 2, 150, 2, 3)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, 150, 150, 12)

    def stem_s2d(w, img):
        x = normalize(img, "tf").astype(jnp.bfloat16)
        x = s2d(x)
        x = conv(x, w["k1s"], 1)[:, :149, :149, :]
        x = jnp.maximum(x * w["s1"] + w["b1"], 0.0).astype(jnp.bfloat16)
        x = conv(x, w["k2"], 1)
        x = jnp.maximum(x * w["s2"] + w["b2"], 0.0).astype(jnp.bfloat16)
        return x

    # --- variant: im2col stem conv1 ---------------------------------------
    def stem_im2col(w, img):
        x = normalize(img, "tf").astype(jnp.bfloat16)
        patches = jax.lax.conv_general_dilated_patches(
            x, (3, 3), (2, 2), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )  # (B, 149, 149, 27); feature order is channel-major (c, kh, kw)
        k = w["k1"].transpose(2, 0, 1, 3).reshape(27, 32).astype(jnp.bfloat16)
        x = jnp.einsum(
            "bhwk,kc->bhwc", patches, k, preferred_element_type=jnp.float32
        )
        x = jnp.maximum(x * w["s1"] + w["b1"], 0.0).astype(jnp.bfloat16)
        x = conv(x, w["k2"], 1)
        x = jnp.maximum(x * w["s2"] + w["b2"], 0.0).astype(jnp.bfloat16)
        return x

    # --- harness ----------------------------------------------------------
    img_small = jax.device_put(
        rng.integers(0, 256, (2, 299, 299, 3), np.uint8), dev
    )
    ref_out = np.asarray(jax.jit(stem_ref)(W, img_small), np.float32)

    variants = {"ref": (stem_ref, W), "s2d": (stem_s2d, Ws), "im2col": (stem_im2col, W)}
    for name, (fn, w) in variants.items():
        if name != "ref":
            got = np.asarray(jax.jit(fn)(w, img_small), np.float32)
            err = np.abs(got - ref_out).max() / (np.abs(ref_out).max() + 1e-6)
            print(f"{name}: max rel err vs ref = {err:.2e}")
            assert err < 2e-2, f"{name} diverges"

    img = jax.device_put(
        rng.integers(0, 256, (args.batch, 299, 299, 3), np.uint8), dev
    )

    for name, (fn, w) in variants.items():
        @partial(jax.jit, static_argnums=2)
        def chained(v, x, k, fn=fn):
            def body(carry, _):
                acc, xi = carry
                s = fn(v, xi).sum()
                bit = jnp.signbit(s).astype(xi.dtype)
                return (acc + s.astype(jnp.float32), xi ^ bit), None

            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), x), None, length=k
            )
            return acc

        float(chained(w, img, args.scan_len))
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(chained(w, img, args.scan_len))
            times.append((time.perf_counter() - t0) / args.scan_len)
        t = float(np.median(times))
        print(f"stem[{name:7s}]: {t * 1e3:8.3f} ms / batch {args.batch}")


if __name__ == "__main__":
    main()
