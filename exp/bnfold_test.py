"""Does serving-time BatchNorm folding speed up the full Xception forward?

Folds every inference-mode BN into its preceding conv: kernel *= gamma/
sqrt(var+eps) per output channel; BN params are rewritten to the identity
transform carrying the residual shift (scale=1, mean=0, var=1-eps,
bias=beta-mean*gamma/sqrt(var+eps)), so the SAME flax module applies and the
tree structure is unchanged.  Checks numerics against the unfolded model,
then times both with the anti-LICM chained scan.
"""

from __future__ import annotations

import argparse
import time
from functools import partial

import numpy as np


def fold_batchnorm(variables, eps: float = 1e-3):
    """Return variables with conv->BN pairs folded (same tree structure)."""
    import jax

    params = jax.tree_util.tree_map(np.asarray, variables["params"])
    stats = jax.tree_util.tree_map(np.asarray, variables["batch_stats"])

    def fold_pair(conv_tree: dict, bn_p: dict, bn_s: dict, kernel_key: str):
        gamma, beta = bn_p["scale"], bn_p["bias"]
        mean, var = bn_s["mean"], bn_s["var"]
        s = gamma / np.sqrt(var + eps)
        conv_tree[kernel_key] = (conv_tree[kernel_key] * s).astype(
            conv_tree[kernel_key].dtype
        )
        bn_p["scale"] = np.ones_like(gamma)
        bn_p["bias"] = (beta - mean * s).astype(beta.dtype)
        bn_s["mean"] = np.zeros_like(mean)
        bn_s["var"] = np.full_like(var, 1.0 - eps)

    # Xception naming: <name>_bn follows <name>; sepconvs fold into the
    # pointwise kernel (the BN is after the whole separable conv).
    for bn_name in list(stats):
        base = bn_name[: -len("_bn")]
        if base in params and "kernel" in params[base]:
            fold_pair(params[base], params[bn_name], stats[bn_name], "kernel")
        elif base in params and "pointwise" in params[base]:
            fold_pair(params[base]["pointwise"], params[bn_name], stats[bn_name], "kernel")
    return {"params": params, "batch_stats": stats}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--scan-len", type=int, default=8)
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec("clothing-model")
    dev = jax.devices()[0]
    variables = init_variables(spec, seed=0)
    # init gives var=1, mean=0 -- fold would be trivial; randomize stats so
    # the numeric check is meaningful.
    rng = np.random.default_rng(1)
    variables = jax.tree_util.tree_map(np.asarray, variables)

    def jitter(tree):
        for k, v in tree.items():
            if isinstance(v, dict):
                jitter(v)
            elif k in ("mean",):
                tree[k] = rng.normal(0, 0.05, v.shape).astype(v.dtype)
            elif k in ("var",):
                tree[k] = rng.uniform(0.5, 1.5, v.shape).astype(v.dtype)
            elif k in ("scale",):
                tree[k] = rng.uniform(0.8, 1.2, v.shape).astype(v.dtype)

    jitter(variables["batch_stats"])
    jitter(variables["params"])

    folded = fold_batchnorm(variables)
    fwd = build_forward(spec, dtype=jnp.bfloat16)
    fwd_jit = jax.jit(fwd)

    x_small = rng.integers(0, 256, (2, *spec.input_shape), np.uint8)
    a = np.asarray(fwd_jit(variables, x_small))
    b = np.asarray(fwd_jit(folded, x_small))
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    print(f"folded-vs-unfolded max rel logit err: {rel:.2e} (bf16 compute)")

    x = jax.device_put(
        rng.integers(0, 256, (args.batch, *spec.input_shape), np.uint8), dev
    )

    for name, v in (("unfolded", variables), ("folded", folded)):
        v = jax.device_put(v, dev)

        @partial(jax.jit, static_argnums=2)
        def chained(vv, xx, k):
            def body(carry, _):
                acc, xi = carry
                s = fwd(vv, xi).sum()
                bit = jnp.signbit(s).astype(xi.dtype)
                return (acc + s.astype(jnp.float32), xi ^ bit), None

            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), xx), None, length=k
            )
            return acc

        float(chained(v, x, args.scan_len))
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(chained(v, x, args.scan_len))
            times.append((time.perf_counter() - t0) / args.scan_len)
        t = float(np.median(times))
        print(
            f"{name:9s}: {t * 1e3:8.3f} ms / batch {args.batch} "
            f"-> {args.batch / t:8.0f} img/s"
        )


if __name__ == "__main__":
    main()
