"""EfficientNet-B3 fused-MBConv measurement harness (VERDICT r3 #4).

Round 3 left B3 serving at 12% MFU with a one-line "structural
(depthwise-heavy)" dismissal and zero experiments.  This harness measures,
on the real chip:

1. the stock flax B3 forward (what serving runs today),
2. the fused fast path (models.efficientnet_fast: stride-1 MBConv blocks
   as single Pallas kernels, ops.fused_mbconv),
3. optionally a trace-span breakdown of where the remaining time goes.

Method: pipelined bursts (amortizes the dev tunnel's ~70 ms dispatch RTT)
plus a chained-scan cross-check at the headline batch, same discipline as
bench.py.  Numerics are asserted against the flax graph before any timing
is believed.

Usage (TPU):  python exp/mbconv_variants.py --batches 64,128 --reps 3
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def time_pipelined(fn, variables, x, k, reps):
    import jax

    # Real materialization, not just block_until_ready: on the axon tunnel
    # b_u_r is a no-op until the data plane initializes (bench.py's
    # worker-crash root cause), which would turn these timings into host
    # dispatch rates.
    np.asarray(fn(variables, x))
    per = []
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [fn(variables, x) for _ in range(k)]
        jax.block_until_ready(outs)
        np.asarray(outs[-1])
        per.append((time.perf_counter() - t0) / k)
    return float(np.median(per))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="efficientnet-b3-imagenet")
    p.add_argument("--batches", default="64,128")
    p.add_argument("--k", type=int, default=100)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--scan-check", action="store_true",
                   help="also run a data-dependent chained-scan cross-check")
    p.add_argument("--tile-budget-mb", type=int, default=0,
                   help="override ops.fused_mbconv._TILE_BUDGET (MiB): raises "
                        "the fusibility bar so the 75x75 stage-2 blocks fuse "
                        "(bigger bt everywhere too); compile OOM = evidence")
    args = p.parse_args()

    if args.tile_budget_mb:
        from kubernetes_deep_learning_tpu.ops import fused_mbconv

        fused_mbconv._TILE_BUDGET = args.tile_budget_mb << 20
        fused_mbconv.VMEM_LIMIT_BYTES = 110 * 1024 * 1024

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.models.efficientnet_fast import (
        build_fast_forward,
    )
    from kubernetes_deep_learning_tpu.modelspec import get_spec
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    spec = get_spec(args.model)
    dev = jax.devices()[0]
    log(f"device: {dev}; model {spec.name} {spec.input_shape}")
    variables = jax.device_put(init_variables(spec, seed=0), dev)

    flax_fwd = jax.jit(build_forward(spec, dtype=jnp.bfloat16, fast=False))
    inner = build_fast_forward(spec, dtype=jnp.bfloat16)
    fast_fwd = jax.jit(
        lambda v, im: inner(v, normalize(im, spec.preprocessing)).astype(jnp.float32)
    )

    rng = np.random.default_rng(0)
    # Numerics gate first (small batch to keep it quick).
    xs = jax.device_put(
        rng.integers(0, 256, (8, *spec.input_shape), np.uint8), dev
    )
    want = np.asarray(flax_fwd(variables, xs))
    got = np.asarray(fast_fwd(variables, xs))
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    log(f"numerics: max rel diff fused-vs-flax = {rel:.2e}")
    assert rel < 2e-2, "fused path numerically diverges; timing would be meaningless"

    for b in (int(x) for x in args.batches.split(",")):
        x = jax.device_put(
            rng.integers(0, 256, (b, *spec.input_shape), np.uint8), dev
        )
        t_flax = time_pipelined(flax_fwd, variables, x, args.k, args.reps)
        t_fast = time_pipelined(fast_fwd, variables, x, args.k, args.reps)
        log(
            f"batch {b:4d}: flax {t_flax * 1e3:7.2f} ms ({b / t_flax:7.0f} img/s)   "
            f"fused {t_fast * 1e3:7.2f} ms ({b / t_fast:7.0f} img/s)   "
            f"speedup {t_flax / t_fast:5.2f}x"
        )
        if args.scan_check:
            from functools import partial

            @partial(jax.jit, static_argnums=(2, 3))
            def chained(v, x, k, use_fast):
                fn = (lambda v, im: inner(v, normalize(im, spec.preprocessing))
                      .astype(jnp.float32)) if use_fast else \
                     build_forward(spec, dtype=jnp.bfloat16, fast=False)

                def body(carry, _):
                    acc, xi = carry
                    s = fn(v, xi).sum()
                    bit = jnp.signbit(s).astype(xi.dtype)
                    return (acc + s.astype(jnp.float32), xi ^ bit), None

                (acc, _), _ = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), x), None, length=k
                )
                return acc

            for use_fast, tag in ((False, "flax"), (True, "fused")):
                # Capped like bench.py's auto-k: single executions past
                # ~30 s get the TPU worker killed (BENCH.md investigation).
                kk = max(24, min(500, int(2.0 / (t_fast if use_fast else t_flax))))
                float(chained(variables, x, kk, use_fast))  # compile+run
                t0 = time.perf_counter()
                float(chained(variables, x, kk, use_fast))
                dt = (time.perf_counter() - t0) / kk
                log(f"   scan-check {tag}: {dt * 1e3:7.2f} ms/iter "
                    f"({b / dt:7.0f} img/s)")


if __name__ == "__main__":
    main()
