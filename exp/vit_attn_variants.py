"""Why is flash attention 46% of ViT-B's device time, and what fixes it?

Round-4 trace (exp/batch_dip_trace.py --model vit-b16-imagenet): each of
the 12 flash custom calls costs 0.64-0.66 ms/iter at batch 32 -- ~5% MFU
-- and switching the in-kernel dots from f32 to bf16 changed NOTHING, so
the kernel is grid-overhead-bound (384 x 2 steps of ~4 MFLOP each, ~1.7
us/step), not MXU-rate-bound.

Measures device span (profiler trace) of attention variants at ViT-B
serving shape (B=32, H=12, S=256, D=64, bf16):

- flash-128: the shipped kernel (block_q=128, grid (384, 2))
- flash-256: block_q=256 (grid (384, 1): half the steps)
- einsum:    mha_reference (XLA path: materializes (B,H,S,S) scores)

Usage: python exp/vit_attn_variants.py [--batch 32]
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def device_span_ms(fn, args_, iters: int) -> float:
    import jax

    jax.block_until_ready(fn(*args_))  # compile
    trace_dir = tempfile.mkdtemp(prefix="kdlt-attnvar-")
    with jax.profiler.trace(trace_dir):
        for _ in range(iters):
            jax.block_until_ready(fn(*args_))
    files = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    assert files, f"no trace files under {trace_dir}"
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)
    pids = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids[ev["pid"]] = ev["args"].get("name", "")
    dev = {p for p, n in pids.items() if n.startswith("/device:TPU")}
    total = 0.0
    for ev in trace["traceEvents"]:
        if (
            ev.get("ph") == "X"
            and ev.get("pid") in dev
            and not ev.get("name", "").startswith("jit_")
        ):
            total += ev.get("dur", 0) / 1e3
    return total / iters


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--iters", type=int, default=30)
    args = p.parse_args()

    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_deep_learning_tpu.ops import attention

    rng = np.random.default_rng(0)
    shape = (args.batch, args.heads, args.seq, args.dim)
    q, k, v = (
        jax.device_put(rng.normal(0, 1, shape).astype(np.float32)).astype(
            jnp.bfloat16
        )
        for _ in range(3)
    )

    ref = jax.jit(attention.mha_reference)
    want = np.asarray(ref(q, k, v), np.float32)
    flops = 2 * 2 * args.batch * args.heads * args.seq * args.seq * args.dim

    variants = [
        ("flash-128x128", jax.jit(functools.partial(attention.flash_attention, block_q=128))),
        ("flash-256x128", jax.jit(functools.partial(attention.flash_attention, block_q=256))),
        # What pick_block actually ships for 256-multiple S: 256 on BOTH
        # sides (callers pass one block to block_q and block_k alike).
        ("flash-256x256", jax.jit(functools.partial(
            attention.flash_attention, block_q=256, block_k=256))),
        ("einsum", ref),
    ]
    print(f"B={args.batch} H={args.heads} S={args.seq} D={args.dim} bf16; "
          f"{flops / 1e9:.2f} GFLOP per attention")
    for name, fn in variants:
        got = np.asarray(fn(q, k, v), np.float32)
        rel = float(np.abs(got - want).max() / (np.abs(want).max() + 1e-9))
        ms = device_span_ms(fn, (q, k, v), args.iters)
        print(
            f"{name:10s}  {ms:7.3f} ms  {flops / ms / 1e9:6.1f} GFLOP/s"
            f"  max-rel {rel:.1e}"
        )


if __name__ == "__main__":
    main()
