"""Why is flash attention 46% of ViT-B's device time, and what fixes it?

Round-4 trace (exp/batch_dip_trace.py --model vit-b16-imagenet): each of
the 12 flash custom calls costs 0.64-0.66 ms/iter at batch 32 -- ~5% MFU
-- and switching the in-kernel dots from f32 to bf16 changed NOTHING, so
the kernel is grid-overhead-bound (384 x 2 steps of ~4 MFLOP each, ~1.7
us/step), not MXU-rate-bound.

Measures device span (profiler trace) of attention variants at ViT-B
serving shape (B=32, H=12, S=256, D=64, bf16):

- flash-128x128: the round-3 kernel tiling (block_q=128, block_k=128)
- flash-256x128: block_q=256 only (half the grid steps)
- flash-256x256: what pick_block ships since round 4 (256 both sides)
- flash-g4/g8:   G-folded local kernel (see flash_gfold): g (batch, head)
                 pairs per grid step -- wins 1.4x more at S=256 but is
                 within noise of 256x256 at S>=1024, where flash actually
                 ships (serving routes S<=512 to einsum); not shipped
- einsum:        mha_reference (XLA path: materializes (B,H,S,S) scores)

Usage: python exp/vit_attn_variants.py [--batch 32]
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def device_span_ms(fn, args_, iters: int) -> float:
    import jax

    jax.block_until_ready(fn(*args_))  # compile
    trace_dir = tempfile.mkdtemp(prefix="kdlt-attnvar-")
    with jax.profiler.trace(trace_dir):
        for _ in range(iters):
            jax.block_until_ready(fn(*args_))
    files = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    assert files, f"no trace files under {trace_dir}"
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)
    pids = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids[ev["pid"]] = ev["args"].get("name", "")
    dev = {p for p, n in pids.items() if n.startswith("/device:TPU")}
    total = 0.0
    for ev in trace["traceEvents"]:
        if (
            ev.get("ph") == "X"
            and ev.get("pid") in dev
            and not ev.get("name", "").startswith("jit_")
        ):
            total += ev.get("dur", 0) / 1e3
    return total / iters


def flash_gfold(q, k, v, *, g: int, block_q: int = 256, block_k: int = 256):
    """G-folded flash: ``g`` (batch, head) pairs per grid step.

    The shipped kernel's grid iterates every (b*h, q-tile) pair, and at
    D=64 each step carries so little work that fixed per-step cost
    dominates (ROADMAP "flash forward at D=64 remains overhead-bound").
    Folding g pairs into one step multiplies per-step work by g and cuts
    steps by g; the in-kernel body just loops over the fold (python
    unroll).  Non-causal only -- the serving/ring forward regime.
    """
    import math

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    assert bh % g == 0 and sq % block_q == 0 and sk % block_k == 0
    qf = q.reshape(bh, sq, d)
    kf = k.reshape(bh, sk, d)
    vf = v.reshape(bh, sk, d)

    def kernel(q_ref, k_ref, v_ref, o_ref):
        scale = 1.0 / math.sqrt(d)
        num_k = sk // block_k
        for gi in range(g):
            qg = q_ref[gi]                       # (block_q, d)

            def body(j, carry):
                acc, m, l = carry
                k_blk = k_ref[gi, pl.ds(j * block_k, block_k), :]
                v_blk = v_ref[gi, pl.ds(j * block_k, block_k), :]
                s = jax.lax.dot_general(
                    qg, k_blk, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale
                m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m - m_new)
                l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
                acc = acc * alpha + jax.lax.dot_general(
                    p.astype(qg.dtype), v_blk, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return acc, m_new, l

            acc = jnp.zeros((block_q, d), jnp.float32)
            m = jnp.full((block_q, 1), -1e30, jnp.float32)
            l = jnp.zeros((block_q, 1), jnp.float32)
            acc, m, l = jax.lax.fori_loop(0, num_k, body, (acc, m, l))
            o_ref[gi] = (acc / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(bh // g, sq // block_q),
        in_specs=[
            pl.BlockSpec((g, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((g, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((g, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((g, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=jax.devices()[0].platform != "tpu",
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--iters", type=int, default=30)
    args = p.parse_args()

    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_deep_learning_tpu.ops import attention

    rng = np.random.default_rng(0)
    shape = (args.batch, args.heads, args.seq, args.dim)
    q, k, v = (
        jax.device_put(rng.normal(0, 1, shape).astype(np.float32)).astype(
            jnp.bfloat16
        )
        for _ in range(3)
    )

    ref = jax.jit(attention.mha_reference)
    want = np.asarray(ref(q, k, v), np.float32)
    flops = 2 * 2 * args.batch * args.heads * args.seq * args.seq * args.dim

    variants = [
        ("flash-128x128", jax.jit(functools.partial(attention.flash_attention, block_q=128))),
        ("flash-256x128", jax.jit(functools.partial(attention.flash_attention, block_q=256))),
        # What pick_block actually ships for 256-multiple S: 256 on BOTH
        # sides (callers pass one block to block_q and block_k alike).
        ("flash-256x256", jax.jit(functools.partial(
            attention.flash_attention, block_q=256, block_k=256))),
        ("einsum", ref),
    ]
    for g in (4, 8):
        if (args.batch * args.heads) % g == 0:
            variants.insert(
                -1,
                (
                    f"flash-g{g}",
                    jax.jit(functools.partial(flash_gfold, g=g)),
                ),
            )
    print(f"B={args.batch} H={args.heads} S={args.seq} D={args.dim} bf16; "
          f"{flops / 1e9:.2f} GFLOP per attention")
    for name, fn in variants:
        got = np.asarray(fn(q, k, v), np.float32)
        rel = float(np.abs(got - want).max() / (np.abs(want).max() + 1e-9))
        ms = device_span_ms(fn, (q, k, v), args.iters)
        print(
            f"{name:10s}  {ms:7.3f} ms  {flops / ms / 1e9:6.1f} GFLOP/s"
            f"  max-rel {rel:.1e}"
        )


if __name__ == "__main__":
    main()
