"""Full Xception forward with the middle flow replaced by the fused v3
Pallas chain -- the honest comparison (the standalone block harness inflates
XLA's cost ~3x vs its in-model fusions).

Extracts the 8 middle blocks' weights from the real flax variables (BN
folded to scale/shift as the kernel expects), transposes NHWC -> (H,W,B,C)
once at middle-flow entry, runs 8 chained pallas blocks, transposes back,
and continues with the stock exit flow.  Checks logits vs build_forward.
"""

from __future__ import annotations

import argparse
import time
from functools import partial

import numpy as np

BN_EPS = 1e-5  # flax.linen.BatchNorm default


def middle_weights_from_variables(variables):
    """(dw, pw, scale, shift) stacked per middle block, BN folded."""
    import jax.numpy as jnp

    params = variables["params"]
    stats = variables["batch_stats"]
    blocks = []
    for idx in range(5, 13):
        dws, pws, ss, bs = [], [], [], []
        for j in (1, 2, 3):
            sep = params[f"block{idx}_sepconv{j}"]
            bn_p = params[f"block{idx}_sepconv{j}_bn"]
            bn_s = stats[f"block{idx}_sepconv{j}_bn"]
            dw = np.asarray(sep["depthwise"]["kernel"])  # (3,3,1,C)
            pw = np.asarray(sep["pointwise"]["kernel"])  # (1,1,C,C)
            gamma, beta = np.asarray(bn_p["scale"]), np.asarray(bn_p["bias"])
            mean, var = np.asarray(bn_s["mean"]), np.asarray(bn_s["var"])
            s = gamma / np.sqrt(var + BN_EPS)
            dws.append(dw[:, :, 0, :])
            pws.append(pw[0, 0])
            ss.append(s)
            bs.append(beta - mean * s)
        blocks.append(
            (
                jnp.asarray(np.stack(dws), jnp.float32),
                jnp.asarray(np.stack(pws), jnp.bfloat16),
                jnp.asarray(np.stack(ss), jnp.float32),
                jnp.asarray(np.stack(bs), jnp.float32),
            )
        )
    return blocks


def build_fused_forward(spec, variables, bt=8):
    """forward(images uint8) -> logits, middle flow via pallas v3."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from exp.fused_middle import fused_block_v3
    from kubernetes_deep_learning_tpu.models.layers import (
        ClassifierHead,
        SeparableConv2D,
        batch_norm,
    )
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    mw = middle_weights_from_variables(variables)
    dtype = jnp.bfloat16

    class XceptionFusedMiddle(nn.Module):
        @nn.compact
        def __call__(self, x):
            conv = partial(nn.Conv, use_bias=False, dtype=dtype)
            bn = partial(batch_norm, False, dtype)
            sep = partial(SeparableConv2D, dtype=dtype)
            pool = partial(
                nn.max_pool, window_shape=(3, 3), strides=(2, 2), padding="SAME"
            )
            x = conv(32, (3, 3), strides=2, padding="VALID", name="block1_conv1")(x)
            x = nn.relu(bn("block1_conv1_bn")(x))
            x = conv(64, (3, 3), padding="VALID", name="block1_conv2")(x)
            x = nn.relu(bn("block1_conv2_bn")(x))
            for idx, feat in ((2, 128), (3, 256), (4, 728)):
                residual = conv(feat, (1, 1), strides=2, padding="SAME", name=f"block{idx}_res_conv")(x)
                residual = bn(f"block{idx}_res_bn")(residual)
                if idx > 2:
                    x = nn.relu(x)
                x = sep(feat, name=f"block{idx}_sepconv1")(x)
                x = bn(f"block{idx}_sepconv1_bn")(x)
                x = nn.relu(x)
                x = sep(feat, name=f"block{idx}_sepconv2")(x)
                x = bn(f"block{idx}_sepconv2_bn")(x)
                x = pool(x) + residual
            # --- fused middle flow ---
            xt = x.transpose(1, 2, 0, 3)  # (H, W, B, C)
            for dw, pw, s, b in mw:
                xt = fused_block_v3(xt, dw, pw, s, b, bt=bt)
            x = xt.transpose(2, 0, 1, 3)
            # --- exit flow (stock) ---
            residual = conv(1024, (1, 1), strides=2, padding="SAME", name="block13_res_conv")(x)
            residual = bn("block13_res_bn")(residual)
            x = nn.relu(x)
            x = sep(728, name="block13_sepconv1")(x)
            x = bn("block13_sepconv1_bn")(x)
            x = nn.relu(x)
            x = sep(1024, name="block13_sepconv2")(x)
            x = bn("block13_sepconv2_bn")(x)
            x = pool(x) + residual
            x = sep(1536, name="block14_sepconv1")(x)
            x = nn.relu(bn("block14_sepconv1_bn")(x))
            x = sep(2048, name="block14_sepconv2")(x)
            x = nn.relu(bn("block14_sepconv2_bn")(x))
            return ClassifierHead(
                spec.num_classes, hidden=spec.head_hidden, dtype=dtype, name="head"
            )(x)

    mod = XceptionFusedMiddle()

    def forward(v, images):
        x = normalize(images, spec.preprocessing)
        return mod.apply(v, x).astype(jnp.float32)

    return forward


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--bt", type=int, default=8)
    p.add_argument("--scan-len", type=int, default=8)
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()

    import sys

    sys.path.insert(0, ".")
    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec("clothing-model")
    dev = jax.devices()[0]
    variables = init_variables(spec, seed=0)
    # Jitter BN stats so folding is non-trivial in the numeric check.
    rng = np.random.default_rng(1)

    def jitter(tree):
        for k, v in tree.items():
            if isinstance(v, dict):
                jitter(v)
            elif k == "mean":
                tree[k] = rng.normal(0, 0.05, v.shape).astype(np.float32)
            elif k == "var":
                tree[k] = rng.uniform(0.5, 1.5, v.shape).astype(np.float32)

    variables = jax.tree_util.tree_map(np.asarray, variables)
    jitter(variables["batch_stats"])

    fwd_ref = jax.jit(build_forward(spec, dtype=jnp.bfloat16))
    fwd_fused = jax.jit(build_fused_forward(spec, variables, bt=args.bt))

    x_small = rng.integers(0, 256, (8, *spec.input_shape), np.uint8)
    a = np.asarray(fwd_ref(variables, x_small))
    b = np.asarray(fwd_fused(variables, x_small))
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    print(f"fused-middle model vs ref: max rel logit err {rel:.2e}")
    assert rel < 5e-2, "diverges"

    variables = jax.device_put(variables, dev)
    x = jax.device_put(
        rng.integers(0, 256, (args.batch, *spec.input_shape), np.uint8), dev
    )
    for name, fwd in (("stock", fwd_ref), ("fused_middle", fwd_fused)):
        @partial(jax.jit, static_argnums=2)
        def chained(v, xx, k, fwd=fwd):
            def body(carry, _):
                acc, xi = carry
                s = fwd(v, xi).sum()
                bit = jnp.signbit(s).astype(xi.dtype)
                return (acc + s.astype(jnp.float32), xi ^ bit), None

            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), xx), None, length=k
            )
            return acc

        float(chained(variables, x, args.scan_len))
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(chained(variables, x, args.scan_len))
            times.append((time.perf_counter() - t0) / args.scan_len)
        t = float(np.median(times))
        print(f"{name:13s}: {t * 1e3:8.3f} ms  {args.batch / t:8.0f} img/s")


if __name__ == "__main__":
    main()
