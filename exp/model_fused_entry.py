"""In-model A/B: full Xception forward with/without the fused entry kernel.

Standalone segment timings inflate ~3x vs in-model (round-2 lesson), so the
only verdict that counts is the full forward, anti-LICM chained scan,
cross-checked with pipelined dispatch, at serving-relevant batches.
"""

from __future__ import annotations

import argparse
import functools
import time

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batches", default="48,56,64,128,256")
    p.add_argument("--scan-len", type=int, default=20)
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import init_variables
    from kubernetes_deep_learning_tpu.models.xception_fast import build_fast_forward
    from kubernetes_deep_learning_tpu.modelspec import get_spec
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    spec = get_spec("clothing-model")
    dev = jax.devices()[0]
    print(f"device: {dev}")
    variables = jax.device_put(init_variables(spec, seed=0), dev)
    rng = np.random.default_rng(0)

    def timed(fwd, batch):
        x0 = jnp.asarray(
            normalize(
                jnp.asarray(
                    rng.integers(0, 256, (batch, *spec.input_shape), np.uint8)
                ),
                spec.preprocessing,
            )
        )
        x0 = jax.device_put(x0, dev)

        @functools.partial(jax.jit, static_argnums=2)
        def chained(v, xx, k):
            def body(carry, _):
                acc, xi = carry
                out = fwd(v, xi)
                s = out.sum()
                xi = xi + (jnp.sign(s) * 1e-3).astype(xi.dtype)
                return (acc + s.astype(jnp.float32), xi), None

            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), xx), None, length=k
            )
            return acc

        float(chained(variables, x0, args.scan_len))  # compile
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(chained(variables, x0, args.scan_len))
            times.append((time.perf_counter() - t0) / args.scan_len)
        return float(np.median(times))

    for batch in (int(b) for b in args.batches.split(",")):
        row = [f"batch {batch:4d}:"]
        variants = (
            ("xla-entry", dict(entry_kernel=False)),
            ("kernel-entry", dict(entry_kernel=True)),
            # VERDICT r3 #5: conv1 computed directly in (H, W, B, C) so the
            # kernel's slab gather reads resident-layout data.
            ("kernel-entry+conv1t", dict(entry_kernel=True, conv1_t=True)),
        )
        for name, kw in variants:
            # chunk=False: every arm must measure the MONOLITHIC program --
            # since round 4 the serving default chunks batches 32-64, which
            # would speed up only the xla-entry baseline (entry_kernel arms
            # disable chunking) and under-credit the kernel arms.
            fwd = build_fast_forward(spec, dtype=jnp.bfloat16, chunk=False, **kw)
            ms = timed(fwd, batch) * 1e3
            row.append(f"{name} {ms:8.3f} ms ({batch / ms * 1e3:7.1f} img/s)")
        print("  ".join(row), flush=True)


if __name__ == "__main__":
    main()
