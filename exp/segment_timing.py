"""Attribute Xception forward time to entry/middle/exit segments on device.

Times jitted sub-forwards (entry flow to each cut point, middle flow alone,
exit flow alone) at serving-relevant batch sizes, so the Pallas fusion work
targets the segment that actually dominates.  Each timed fn chains K=8
data-dependent iterations (same anti-LICM trick as bench.py) to amortize the
~70 ms tunnel dispatch RTT on this dev box.

Usage: python exp/segment_timing.py [--batch 256]
"""

from __future__ import annotations

import argparse
import time
from functools import partial

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--scan-len", type=int, default=8)
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import init_variables
    from kubernetes_deep_learning_tpu.models.xception import Xception
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec("clothing-model")
    model = Xception(spec.num_classes, head_hidden=spec.head_hidden, dtype=jnp.bfloat16)
    variables = init_variables(spec, seed=0)
    dev = jax.devices()[0]
    variables = jax.device_put(variables, dev)
    print(f"device: {dev}, batch {args.batch}")

    # Segment boundaries, chosen at the natural Xception flow cuts.  Each
    # segment is expressed as a capture of the full model's intermediate
    # (flax's perturb-free way: run __call__ with a capture_intermediates
    # filter would keep all; instead re-run the model up to a block by
    # monkey-free slicing is messy -- so segments are timed as DELTAS between
    # progressively longer prefixes).
    # prefix k = forward through block k (1=block1 convs, 2..4 entry blocks,
    # 12=middle done, 14=exit convs done, 15=head).
    import flax.linen as nn

    class Prefix(nn.Module):
        upto: int  # inclusive block index; 15 = head included
        dtype: object = jnp.bfloat16

        @nn.compact
        def __call__(self, x):
            from kubernetes_deep_learning_tpu.models.layers import (
                ClassifierHead,
                SeparableConv2D,
                batch_norm,
            )

            conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
            bn = partial(batch_norm, False, self.dtype)
            sep = partial(SeparableConv2D, dtype=self.dtype)
            pool = partial(
                nn.max_pool, window_shape=(3, 3), strides=(2, 2), padding="SAME"
            )
            x = conv(32, (3, 3), strides=2, padding="VALID", name="block1_conv1")(x)
            x = nn.relu(bn("block1_conv1_bn")(x))
            x = conv(64, (3, 3), padding="VALID", name="block1_conv2")(x)
            x = nn.relu(bn("block1_conv2_bn")(x))
            if self.upto <= 1:
                return x
            for idx, feat in ((2, 128), (3, 256), (4, 728)):
                if self.upto < idx:
                    return x
                residual = conv(feat, (1, 1), strides=2, padding="SAME", name=f"block{idx}_res_conv")(x)
                residual = bn(f"block{idx}_res_bn")(residual)
                if idx > 2:
                    x = nn.relu(x)
                x = sep(feat, name=f"block{idx}_sepconv1")(x)
                x = bn(f"block{idx}_sepconv1_bn")(x)
                x = nn.relu(x)
                x = sep(feat, name=f"block{idx}_sepconv2")(x)
                x = bn(f"block{idx}_sepconv2_bn")(x)
                x = pool(x) + residual
            for idx in range(5, 13):
                if self.upto < idx:
                    return x
                residual = x
                for j in (1, 2, 3):
                    x = nn.relu(x)
                    x = sep(728, name=f"block{idx}_sepconv{j}")(x)
                    x = bn(f"block{idx}_sepconv{j}_bn")(x)
                x = x + residual
            if self.upto < 13:
                return x
            residual = conv(1024, (1, 1), strides=2, padding="SAME", name="block13_res_conv")(x)
            residual = bn("block13_res_bn")(residual)
            x = nn.relu(x)
            x = sep(728, name="block13_sepconv1")(x)
            x = bn("block13_sepconv1_bn")(x)
            x = nn.relu(x)
            x = sep(1024, name="block13_sepconv2")(x)
            x = bn("block13_sepconv2_bn")(x)
            x = pool(x) + residual
            if self.upto < 14:
                return x
            x = sep(1536, name="block14_sepconv1")(x)
            x = nn.relu(bn("block14_sepconv1_bn")(x))
            x = sep(2048, name="block14_sepconv2")(x)
            x = nn.relu(bn("block14_sepconv2_bn")(x))
            if self.upto < 15:
                return x
            return ClassifierHead(
                spec.num_classes, hidden=spec.head_hidden, dtype=self.dtype, name="head"
            )(x)

    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    def timed_prefix(upto: int):
        mod = Prefix(upto=upto)

        @partial(jax.jit, static_argnums=2)
        def chained(v, img, k):
            def body(carry, _):
                acc, xi = carry
                out = mod.apply(v, normalize(xi, spec.preprocessing))
                s = out.sum()
                bit = jnp.signbit(s).astype(xi.dtype)
                return (acc + s.astype(jnp.float32), xi ^ bit), None

            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), img), None, length=k
            )
            return acc

        rng = np.random.default_rng(0)
        img = jax.device_put(
            rng.integers(0, 256, (args.batch, *spec.input_shape), np.uint8), dev
        )
        float(chained(variables, img, args.scan_len))  # compile+warm
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(chained(variables, img, args.scan_len))
            times.append((time.perf_counter() - t0) / args.scan_len)
        return float(np.median(times))

    cuts = [1, 2, 3, 4, 12, 14, 15]
    names = {
        1: "block1 convs (299->147x147x64)",
        2: "block2 (147, 64->128, pool->74)",
        3: "block3 (74, 128->256, pool->37)",
        4: "block4 (37, 256->728, pool->19)",
        12: "middle flow (8 blocks @19x19x728)",
        14: "exit flow (blocks 13-14)",
        15: "head + logits",
    }
    prev = 0.0
    total = None
    for c in cuts:
        t = timed_prefix(c)
        total = t
        print(
            f"prefix<=blk{c:2d}: {t * 1e3:8.3f} ms   delta {('%8.3f' % ((t - prev) * 1e3))} ms  {names[c]}"
        )
        prev = t
    b = args.batch
    print(f"full forward: {total * 1e3:.3f} ms -> {b / total:.0f} img/s at batch {b}")


if __name__ == "__main__":
    main()
