"""Pallas prototype: fused Xception entry segment (conv2 + block2).

Covers the trace's top entry-flow fusions (~17.5 ms of the batch-256
forward): block1_conv2 3x3 VALID (32->64) + BN/relu, block2's residual
1x1/2 conv + BN, sepconv1 (64->128) + BN + relu, sepconv2 (128) + BN,
maxpool 3x3/2 + residual add.  Intermediates at 147x147 never touch HBM.

Layout (rows, W, bt, C): batch on sublanes, channels on lanes (same trick
as the middle-flow kernel); spatial tiled over OUTPUT rows with halo rows
on the input.  conv2 runs as in-kernel im2col (9 lane-concatenated shifted
slices -> one (M, 288) @ (288, 64) GEMM); depthwise convs are shifted FMAs
on outer dims; pool/residual use stride-2 outer-dim slices.

Validates against the plain-jnp reference, then times vs the XLA graph.
"""

from __future__ import annotations

import argparse
import functools
import time

import numpy as np

H_IN = 149   # conv1 output spatial (input to this kernel)
H_B = 147    # after conv2 VALID
H_OUT = 74   # after pool stride 2 SAME
C_IN, C_B, C_OUT = 32, 64, 128


def make_weights(rng):
    import jax.numpy as jnp

    w = {
        "conv2": rng.normal(0, 0.1, (3, 3, C_IN, C_B)).astype(np.float32),
        "conv2_s": rng.uniform(0.8, 1.2, C_B).astype(np.float32),
        "conv2_b": rng.normal(0, 0.1, C_B).astype(np.float32),
        "res": rng.normal(0, 0.1, (C_B, C_OUT)).astype(np.float32),
        "res_s": rng.uniform(0.8, 1.2, C_OUT).astype(np.float32),
        "res_b": rng.normal(0, 0.1, C_OUT).astype(np.float32),
        "dw1": rng.normal(0, 0.2, (3, 3, C_B)).astype(np.float32),
        "pw1": rng.normal(0, 0.05, (C_B, C_OUT)).astype(np.float32),
        "bn1_s": rng.uniform(0.8, 1.2, C_OUT).astype(np.float32),
        "bn1_b": rng.normal(0, 0.1, C_OUT).astype(np.float32),
        "dw2": rng.normal(0, 0.2, (3, 3, C_OUT)).astype(np.float32),
        "pw2": rng.normal(0, 0.05, (C_OUT, C_OUT)).astype(np.float32),
        "bn2_s": rng.uniform(0.8, 1.2, C_OUT).astype(np.float32),
        "bn2_b": rng.normal(0, 0.1, C_OUT).astype(np.float32),
    }
    return {k: jnp.asarray(v) for k, v in w.items()}


def entry_ref(a, w):
    """Plain-jnp reference, NHWC (B, 149, 149, 32) bf16 -> (B, 74, 74, 128)."""
    import jax
    import jax.numpy as jnp

    def conv(x, k, stride=1, padding="VALID", fgc=1):
        return jax.lax.conv_general_dilated(
            x, k.astype(x.dtype), (stride, stride), padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=fgc,
        )

    b = conv(a, w["conv2"])  # (B,147,147,64)
    b = jnp.maximum(
        (b.astype(jnp.float32) * w["conv2_s"] + w["conv2_b"]), 0
    ).astype(jnp.bfloat16)
    r = jnp.einsum("bhwc,cd->bhwd", b[:, ::2, ::2, :], w["res"].astype(jnp.bfloat16))
    r = (r.astype(jnp.float32) * w["res_s"] + w["res_b"]).astype(jnp.bfloat16)
    c = conv(b, w["dw1"][:, :, None, :].astype(jnp.bfloat16), padding="SAME", fgc=C_B)
    c = jnp.einsum("bhwc,cd->bhwd", c, w["pw1"].astype(jnp.bfloat16))
    c = jnp.maximum(
        c.astype(jnp.float32) * w["bn1_s"] + w["bn1_b"], 0
    ).astype(jnp.bfloat16)
    d = conv(c, w["dw2"][:, :, None, :].astype(jnp.bfloat16), padding="SAME", fgc=C_OUT)
    d = jnp.einsum("bhwc,cd->bhwd", d, w["pw2"].astype(jnp.bfloat16))
    d = (d.astype(jnp.float32) * w["bn2_s"] + w["bn2_b"]).astype(jnp.bfloat16)
    pooled = jax.lax.reduce_window(
        d, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    return pooled + r


def fused_entry(a_t, w, *, bt=8, rt=10, interpret=False):
    """Kernel on (149, 149, B, 32) bf16 -> (74, 74, B, 128).

    Grid: (ceil(74/rt), B // bt).  Each instance computes ``rt`` output rows
    for ``bt`` images.  Overlapping input row windows are not expressible in
    BlockSpec units, so the input is pre-gathered into per-tile slabs
    (n_tiles, ht_a, Wp, B, 32) in XLA-land -- ~25% extra input traffic, the
    simple-first trade (manual HBM DMA with dynamic offsets would avoid it).

    Geometry (all offsets static): tile g covers output rows
    [rt*g, rt*g+rt).  The SAME max-pool for 147 -> 74 pads (1,1), so out
    row i's window is d rows 2i-1 .. 2i+1; through the two SAME dws (+-1
    each) the tile needs b rows [2*rt*g - 3, 2*rt*g + 2*rt + 2) => ht_b =
    2*rt + 5 with row0_b = 2*rt*g - 3, and a rows [row0_b, row0_b + ht_a),
    ht_a = ht_b + 2 (conv2 VALID).  The padded-a slab makes every slice
    in-range; a validity mask re-zeroes rows the BN affines contaminate.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _, _, B, _ = a_t.shape
    bt = min(bt, B)
    n_tiles = -(-H_OUT // rt)
    ht_b = 2 * rt + 5
    ht_a = ht_b + 2
    # Pad: a row for global b row -2 is global a row -2 -> top pad 2; right
    # W pad 2 for conv2's VALID reach (149 cols -> col index up to 148+2).
    # Slab g reads PADDED rows [2*rt*g, +ht_a); the padded array has
    # 3 + H_IN + bottom rows and must cover the last slab (top pad 3:
    # slab g starts at global a row 2*rt*g - 3).
    bottom = max(0, 2 * rt * (n_tiles - 1) + ht_a - (H_IN + 3))
    a_pad = jnp.pad(a_t, ((3, bottom), (0, 2), (0, 0), (0, 0)))
    Wp = H_IN + 2  # 151
    # Pre-gathered overlapping slabs: slab g = padded rows [2*rt*g, +ht_a).
    slabs = jnp.stack(
        [a_pad[2 * rt * g : 2 * rt * g + ht_a] for g in range(n_tiles)]
    )  # (n_tiles, ht_a, Wp, B, C_IN)

    def kernel(a_ref, cv_ref, cvs_ref, cvb_ref, res_ref, ress_ref, resb_ref,
               dw1_ref, pw1_ref, s1_ref, b1_ref, dw2_ref, pw2_ref, s2_ref,
               b2_ref, o_ref):
        g_r = pl.program_id(0)
        a = a_ref[0]  # (ht_a, Wp, bt, 32)

        # --- conv2 3x3 VALID: im2col on lanes -> ONE K=288 GEMM ------------
        # (9 accumulated K=32 GEMMs waste 3/4 of each MXU pass.)
        patches = jnp.concatenate(
            [
                a[dh : dh + ht_b, dwc : dwc + H_B, :, :]
                for dh in range(3)
                for dwc in range(3)
            ],
            axis=-1,
        )  # (ht_b, 147, bt, 288), taps (dh, dwc)-major like cv's reshape
        z = jax.lax.dot_general(
            patches.reshape(ht_b * H_B * bt, 9 * C_IN),
            cv_ref[...].reshape(9 * C_IN, C_B).astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        b = jnp.maximum(z * cvs_ref[...] + cvb_ref[...], 0).astype(
            jnp.bfloat16
        ).reshape(ht_b, H_B, bt, C_B)

        # Validity of local b rows: global b row = 2*rt*g - 3 + L.  The mask
        # carries full (bt, C) extent: Mosaic cannot broadcast one value
        # over sublanes AND lanes at once, but broadcasting over the
        # untiled dim 1 is free.
        row0_b = 2 * rt * g_r - 3

        def row_mask(c):
            rows = (
                jax.lax.broadcasted_iota(jnp.int32, (ht_b, 1, bt, c), 0)
                + row0_b
            )
            return (rows >= 0) & (rows < H_B)  # bool (int compares only:
            # Mosaic has no bf16 comparison)

        valid_b = row_mask(C_B)
        b = b * valid_b.astype(jnp.bfloat16)

        # --- residual: 1x1 stride-2 on b (row0_b odd: local 3,5,... are the
        # global even rows 2*rt*g, 2*rt*g + 2, ...).  Stride-2 selection is
        # slice+reshape on OUTER dims (a double-strided slice lowers to an
        # unsupported gather in Mosaic). ------------------------------------
        def every_other(x, start, count, axis):
            idx = [slice(None)] * x.ndim
            idx[axis] = slice(start, start + 2 * count)
            x = x[tuple(idx)]
            shape = list(x.shape)
            shape[axis : axis + 1] = [count, 2]
            x = x.reshape(shape)
            idx = [slice(None)] * x.ndim
            idx[axis + 1] = 0
            out = x[tuple(idx)]
            return out.reshape(
                [s for i, s in enumerate(x.shape) if i != axis + 1]
            )

        b_rows = every_other(b, 3, rt + 1, 0)  # (rt+1, 147, bt, C_B)
        b_rows = jnp.pad(b_rows, ((0, 0), (0, 1), (0, 0), (0, 0)))  # cols 148
        b_even = every_other(b_rows, 0, (H_B + 1) // 2, 1)
        hr, wr = b_even.shape[0], b_even.shape[1]
        r = jax.lax.dot_general(
            b_even.reshape(hr * wr * bt, C_B),
            res_ref[...].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        r = (r * ress_ref[...] + resb_ref[...]).astype(jnp.bfloat16).reshape(
            hr, wr, bt, C_OUT
        )

        # --- sepconvs ------------------------------------------------------
        def dw(x, dwk):
            xp = jnp.pad(x, ((1, 1), (1, 1), (0, 0), (0, 0)))
            acc = jnp.zeros(x.shape, jnp.float32)
            for dh in range(3):
                for dwc in range(3):
                    acc = acc + (
                        xp[dh : dh + x.shape[0], dwc : dwc + x.shape[1], :, :]
                        .astype(jnp.float32) * dwk[dh, dwc, :]
                    )
            return acc

        c = dw(b, dw1_ref[...])
        c = jax.lax.dot_general(
            c.astype(jnp.bfloat16).reshape(ht_b * H_B * bt, C_B),
            pw1_ref[...].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        c = jnp.maximum(c * s1_ref[...] + b1_ref[...], 0).astype(
            jnp.bfloat16
        ).reshape(ht_b, H_B, bt, C_OUT)
        valid_out = row_mask(C_OUT)
        c = c * valid_out.astype(jnp.bfloat16)  # re-zero contaminated rows

        d = dw(c, dw2_ref[...])
        d = jax.lax.dot_general(
            d.astype(jnp.bfloat16).reshape(ht_b * H_B * bt, C_OUT),
            pw2_ref[...].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        d = (d * s2_ref[...] + b2_ref[...]).reshape(ht_b, H_B, bt, C_OUT)
        # Invalid rows must lose the max-pool, not win it.
        d = jnp.where(valid_out, d, -1e9).astype(jnp.bfloat16)
        # SAME pool (1,1) col padding: out col c's window is cols 2c-1..2c+1.
        d = jnp.pad(d, ((0, 0), (1, 1), (0, 0), (0, 0)), constant_values=-1e9)

        # --- maxpool 3x3/2 + residual --------------------------------------
        # Out row j of this tile: window d rows 2*(rt*g+j)-1 .. +1, local
        # (with row0_b = 2*rt*g - 3) = 2j+2 .. 2j+4; padded cols give
        # window col index 2c + dwc.  Same slice+reshape stride-2 trick.
        # d is (ht_b, 149, bt, C_OUT) after the col pad; pad one more col so
        # stride-2 col selections of 75 entries stay in range, plus a spare
        # row for the dh=2 slice of the last window.
        d = jnp.pad(
            d, ((0, 1), (0, 1), (0, 0), (0, 0)), constant_values=-1e9
        )
        pooled = None
        for dh in range(3):
            for dwc in range(3):
                sl = every_other(d, 2 + dh, rt, 0)
                sl = every_other(sl, dwc, H_OUT, 1)
                pooled = sl if pooled is None else jnp.maximum(pooled, sl)
        o_ref[0] = pooled + r[:rt, :H_OUT, :, :]

    grid = (n_tiles, B // bt)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, ht_a, Wp, bt, C_IN), lambda gr, gb: (gr, 0, 0, gb, 0)),
            pl.BlockSpec((3, 3, C_IN, C_B), lambda gr, gb: (0, 0, 0, 0)),
            pl.BlockSpec((C_B,), lambda gr, gb: (0,)),
            pl.BlockSpec((C_B,), lambda gr, gb: (0,)),
            pl.BlockSpec((C_B, C_OUT), lambda gr, gb: (0, 0)),
            pl.BlockSpec((C_OUT,), lambda gr, gb: (0,)),
            pl.BlockSpec((C_OUT,), lambda gr, gb: (0,)),
            pl.BlockSpec((3, 3, C_B), lambda gr, gb: (0, 0, 0)),
            pl.BlockSpec((C_B, C_OUT), lambda gr, gb: (0, 0)),
            pl.BlockSpec((C_OUT,), lambda gr, gb: (0,)),
            pl.BlockSpec((C_OUT,), lambda gr, gb: (0,)),
            pl.BlockSpec((3, 3, C_OUT), lambda gr, gb: (0, 0, 0)),
            pl.BlockSpec((C_OUT, C_OUT), lambda gr, gb: (0, 0)),
            pl.BlockSpec((C_OUT,), lambda gr, gb: (0,)),
            pl.BlockSpec((C_OUT,), lambda gr, gb: (0,)),
        ],
        out_specs=pl.BlockSpec(
            (1, rt, H_OUT, bt, C_OUT), lambda gr, gb: (gr, 0, 0, gb, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (n_tiles, rt, H_OUT, B, C_OUT), jnp.bfloat16
        ),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(
        slabs, w["conv2"], w["conv2_s"], w["conv2_b"], w["res"], w["res_s"],
        w["res_b"], w["dw1"], w["pw1"], w["bn1_s"], w["bn1_b"], w["dw2"],
        w["pw2"], w["bn2_s"], w["bn2_b"],
    )
    # (n_tiles, rt, 74, B, 128) -> (74(+crop), 74, B, 128)
    return out.reshape(n_tiles * rt, H_OUT, B, C_OUT)[:H_OUT]


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--bt", type=int, default=8)
    p.add_argument("--rt", type=int, default=10)
    p.add_argument("--scan-len", type=int, default=8)
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--interpret", action="store_true")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"device: {dev}, batch {args.batch}, bt {args.bt}, rt {args.rt}")
    rng = np.random.default_rng(0)
    w = make_weights(rng)

    a_small = jnp.asarray(rng.normal(0, 0.5, (8, H_IN, H_IN, C_IN)), jnp.bfloat16)
    want = np.asarray(entry_ref(a_small, w), np.float32)
    got = np.asarray(
        jax.jit(
            functools.partial(fused_entry, bt=8, rt=args.rt, interpret=args.interpret)
        )(a_small.transpose(1, 2, 0, 3), w).transpose(2, 0, 1, 3),
        np.float32,
    )
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    print(f"fused entry vs ref: max rel err {rel:.2e}")
    assert rel < 3e-2, "diverges"
    if args.interpret:
        print("interpret-mode PASS")
        return

    a = jax.device_put(
        jnp.asarray(rng.normal(0, 0.5, (args.batch, H_IN, H_IN, C_IN)), jnp.bfloat16),
        dev,
    )

    for name, fn in (
        ("asis", lambda x, w: entry_ref(x, w)),
        (
            "fused",
            lambda x, w: fused_entry(
                x.transpose(1, 2, 0, 3), w, bt=args.bt, rt=args.rt
            ).transpose(2, 0, 1, 3),
        ),
    ):
        @functools.partial(jax.jit, static_argnums=2)
        def chained(xx, ww, k, fn=fn):
            def body(carry, _):
                acc, xi = carry
                out = fn(xi, ww)
                s = out.sum()
                xi = xi + (jnp.sign(s) * 1e-3).astype(xi.dtype)
                return (acc + s.astype(jnp.float32), xi), None

            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), xx), None, length=k
            )
            return acc

        try:
            float(chained(a, w, args.scan_len))
            times = []
            for _ in range(args.reps):
                t0 = time.perf_counter()
                float(chained(a, w, args.scan_len))
                times.append((time.perf_counter() - t0) / args.scan_len)
            print(f"{name:6s}: {float(np.median(times)) * 1e3:8.3f} ms")
        except Exception as e:
            print(f"{name:6s}: FAILED {str(e).splitlines()[0][:140]}")


if __name__ == "__main__":
    main()
