"""Where does the Xception middle flow lose 2/3 of peak, and what fixes it?

One middle block = 3x [relu -> depthwise 3x3 (728ch) -> pointwise 728x728 ->
BN] + residual, at 19x19 spatial.  Variants timed at serving batch:

- asis:      conv_general_dilated with feature_group_count (what flax emits)
- dw_shift:  depthwise as 9 shifted multiply-adds (VPU-friendly, no conv op)
- pw_only:   depthwise deleted (lower bound = pure GEMM + elementwise)
- dw_only /  the isolated depthwise cost both ways
  dws_only

All share weights; numerics cross-checked (asis vs dw_shift must agree).
"""

from __future__ import annotations

import argparse
import time
from functools import partial

import numpy as np

C = 728
H = W = 19


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--scan-len", type=int, default=16)
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"device: {dev}, batch {args.batch}, tensor ({args.batch},{H},{W},{C})")
    rng = np.random.default_rng(0)

    dw = [rng.normal(0, 0.2, (3, 3, C)).astype(np.float32) for _ in range(3)]
    pw = [rng.normal(0, 0.03, (C, C)).astype(np.float32) for _ in range(3)]
    scale = [rng.uniform(0.8, 1.2, C).astype(np.float32) for _ in range(3)]
    shift = [rng.normal(0, 0.1, C).astype(np.float32) for _ in range(3)]
    Wt = {
        "dw": [jnp.asarray(k, jnp.bfloat16) for k in dw],
        "pw": [jnp.asarray(k, jnp.bfloat16) for k in pw],
        "s": [jnp.asarray(s) for s in scale],
        "b": [jnp.asarray(b) for b in shift],
    }

    def dw_conv(x, k):  # k (3,3,C); what flax SeparableConv2D emits
        return jax.lax.conv_general_dilated(
            x, k[:, :, None, :].astype(x.dtype), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=C,
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)

    def dw_shifted(x, k):  # 9 shifted multiply-adds, SAME padding
        xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
        acc = jnp.zeros(x.shape, jnp.float32)
        for i in range(3):
            for j in range(3):
                acc = acc + (
                    xp[:, i : i + H, j : j + W, :].astype(jnp.float32)
                    * k[i, j].astype(jnp.float32)
                )
        return acc.astype(x.dtype)

    def pw_mm(x, k):
        return jax.lax.dot_general(
            x, k.astype(x.dtype),
            (((3,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)

    def block(x, w, dw_fn, skip_dw=False):
        y = x
        for i in range(3):
            y = jnp.maximum(y, 0)
            if not skip_dw:
                y = dw_fn(y, w["dw"][i])
            y = pw_mm(y, w["pw"][i])
            y = (y.astype(jnp.float32) * w["s"][i] + w["b"][i]).astype(x.dtype)
        return x + y

    variants = {
        "asis": lambda x, w: block(x, w, dw_conv),
        "dw_shift": lambda x, w: block(x, w, dw_shifted),
        "pw_only": lambda x, w: block(x, w, None, skip_dw=True),
        "dw_only": lambda x, w: dw_conv(dw_conv(dw_conv(x, w["dw"][0]), w["dw"][1]), w["dw"][2]),
        "dws_only": lambda x, w: dw_shifted(dw_shifted(dw_shifted(x, w["dw"][0]), w["dw"][1]), w["dw"][2]),
    }

    x_small = jnp.asarray(
        rng.normal(0, 1, (2, H, W, C)), jnp.bfloat16
    )
    a = np.asarray(jax.jit(variants["asis"])(x_small, Wt), np.float32)
    b = np.asarray(jax.jit(variants["dw_shift"])(x_small, Wt), np.float32)
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    print(f"dw_shift vs asis max rel err: {rel:.2e}")

    x = jax.device_put(
        jnp.asarray(rng.normal(0, 1, (args.batch, H, W, C)), jnp.bfloat16), dev
    )

    # GEMM FLOPs for MFU context: 3 pw per block
    gemm_tf = 3 * args.batch * H * W * C * C * 2 / 1e12

    for name, fn in variants.items():
        @partial(jax.jit, static_argnums=2)
        def chained(xx, w, k, fn=fn):
            def body(carry, _):
                acc, xi = carry
                out = fn(xi, w)
                s = out.sum()
                # data-dependence: nudge the input by a sign-dependent ulp
                xi = xi + (jnp.sign(s) * 1e-3).astype(xi.dtype)
                return (acc + s.astype(jnp.float32), xi), None

            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), xx), None, length=k
            )
            return acc

        float(chained(x, Wt, args.scan_len))
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(chained(x, Wt, args.scan_len))
            times.append((time.perf_counter() - t0) / args.scan_len)
        t = float(np.median(times))
        mfu = gemm_tf / t / 197.0 * 100 if "only" not in name or name == "pw_only" else 0
        extra = f"  (GEMM-only MFU {mfu:4.1f}%)" if mfu else ""
        print(f"{name:9s}: {t * 1e3:8.3f} ms{extra}")


if __name__ == "__main__":
    main()
