"""Where does EfficientNet-B3's forward time go, stage by stage?

Times XLA-graph PREFIXES of the functional B3 forward (stem, then through
the end of each stage), pipelined bursts; successive differences give
per-stage cost.  This is the evidence base for the fused-MBConv verdict
(exp/mbconv_variants.py measured the fused path 0.87x at batch 64): if the
time lives in the high-resolution early stages whose expanded tiles cannot
fit VMEM (ops.fused_mbconv.mbconv_fusible), block-level fusion of the
low-resolution stages cannot move the headline, and B3's 12% MFU is
structural under this design.

CAVEAT (recorded after the fact): burst timing on this box is floored at
~2-5 ms/iteration for light programs (BENCH.md "Measurement floor"), so
the SHORT prefixes here (stem, first stages) read the floor, not their
true sub-millisecond device time, and the first segments absorb that
offset.  The authoritative early-stage attribution for the fused-MBConv
verdict is therefore the per-fusion device-trace table in BENCH.md
(trace spans have no floor); this script remains useful for the LONG
prefixes, where successive differences sit well above the floor.

Usage (TPU): python exp/mbconv_stage_timing.py --batch 64
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--k", type=int, default=60)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import init_variables
    from kubernetes_deep_learning_tpu.models.efficientnet import SCALING
    from kubernetes_deep_learning_tpu.models.layers import KERAS_BN_EPS
    from kubernetes_deep_learning_tpu.models.efficientnet_fast import block_plan
    from kubernetes_deep_learning_tpu.modelspec import get_spec
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    spec = get_spec("efficientnet-b3-imagenet")
    width, depth, _ = SCALING["b3"]
    plan = block_plan(width, depth)
    dtype = jnp.bfloat16
    dev = jax.devices()[0]
    variables = jax.device_put(init_variables(spec, seed=0), dev)

    def conv(x, kernel, stride=1, groups=1):
        return jax.lax.conv_general_dilated(
            x.astype(dtype), jnp.asarray(kernel, dtype), (stride, stride),
            "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups,
        )

    def bn(x, p, s):
        y = (x - jnp.asarray(s["mean"], dtype)) * jax.lax.rsqrt(
            jnp.asarray(s["var"], dtype) + jnp.asarray(KERAS_BN_EPS, dtype)
        )
        return y * jnp.asarray(p["scale"], dtype) + jnp.asarray(p["bias"], dtype)

    def mbconv(x, bp, bs, stride, features, expand):
        c_in = x.shape[-1]
        y = x
        if expand != 1:
            y = conv(y, bp["expand_conv"]["kernel"])
            y = jax.nn.silu(bn(y, bp["expand_bn"], bs["expand_bn"]))
        y = conv(y, bp["dwconv"]["kernel"], stride=stride, groups=y.shape[-1])
        y = jax.nn.silu(bn(y, bp["dw_bn"], bs["dw_bn"]))
        se = bp["se"]
        m = y.mean(axis=(1, 2), keepdims=True)
        r = jax.nn.silu(conv(m, se["reduce"]["kernel"])
                        + jnp.asarray(se["reduce"]["bias"], dtype))
        g = jax.nn.sigmoid(conv(r, se["expand"]["kernel"])
                           + jnp.asarray(se["expand"]["bias"], dtype))
        y = y * g
        y = conv(y, bp["project_conv"]["kernel"])
        y = bn(y, bp["project_bn"], bs["project_bn"])
        if stride == 1 and c_in == features:
            y = y + x
        return y

    def prefix_forward(n_blocks):
        def f(v, img):
            pp, ss = v["params"], v["batch_stats"]
            x = normalize(img, spec.preprocessing)
            x = conv(x, pp["stem_conv"]["kernel"], stride=2)
            x = jax.nn.silu(bn(x, pp["stem_bn"], ss["stem_bn"]))
            for name, stride, _k, feats, expand in plan[:n_blocks]:
                x = mbconv(x, pp[name], ss[name], stride, feats, expand)
            # Cheap sink so nothing is dead-code-eliminated.
            return x.astype(jnp.float32).mean(axis=(1, 2, 3))
        return jax.jit(f)

    rng = np.random.default_rng(0)
    x = jax.device_put(
        rng.integers(0, 256, (args.batch, *spec.input_shape), np.uint8), dev
    )

    # Stage boundaries: index in `plan` after each stage's last block.
    bounds = [0]
    seen = 0
    last_feat = None
    for i, (_n, _s, _k, feats, _e) in enumerate(plan):
        if last_feat is not None and feats != last_feat:
            bounds.append(i)
        last_feat = feats
        seen = i + 1
    bounds.append(seen)

    def timed(fn):
        np.asarray(fn(variables, x))  # compile + data-plane init
        per = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            outs = [fn(variables, x) for _ in range(args.k)]
            jax.block_until_ready(outs)
            np.asarray(outs[-1])
            per.append((time.perf_counter() - t0) / args.k)
        return float(np.median(per))

    prev = 0.0
    log(f"batch {args.batch}; stage boundaries at blocks {bounds}")
    for i, nb in enumerate(bounds):
        t = timed(prefix_forward(nb))
        seg = t - prev
        what = "stem" if nb == 0 else f"..block{nb - 1}"
        shape_note = ""
        if nb > 0:
            _n, _s, _k, feats, _e = plan[nb - 1]
            shape_note = f" (stage features {feats})"
        log(f"prefix {what:>10}{shape_note}: total {t * 1e3:7.2f} ms  "
            f"segment +{seg * 1e3:6.2f} ms")
        prev = t


if __name__ == "__main__":
    main()
