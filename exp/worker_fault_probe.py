"""Root-cause probe for the recurring TPU worker "kernel fault" (VERDICT
r3 weak-1 / next-1b).

Observed fact pattern (rounds 1-4): every crash happened inside the
bench's CHAINED SCAN -- a single jit execution running ~7 s of
back-to-back fused-kernel iterations (auto-k targets 7 s/call) -- never in
pipelined bursts (many short executions), never in serving.  r3: batch-56
point, ViT batch-256 sweep; r4: batch-32 point, twice, plus the batch-48
first attempt while the worker was still recovering.

Hypotheses, one phase per PROCESS (run each as
``python exp/worker_fault_probe.py <phase>``; a fault kills only that
process, and the driver shell inspects the exit):

  pipelined      fused forward, 5 bursts x 200 dispatches (same total
                 device work as one scan call, chopped into ~8 ms
                 executions).  PASS expected if duration-per-execution is
                 the trigger.
  scan-short     chained scan k=100 (~1 s/execution), 8 calls.
  scan-long      chained scan k=900 (~7 s/execution), 3 calls -- the
                 bench's crashing configuration, minimally reproduced.
  scan-long-96m  scan-long with the sepconv kernels' vmem_limit_bytes
                 dropped 110 -> 96 MiB (hypothesis: near-limit VMEM).
  scan-long-exact scan-long on the EXACT flax graph (no Pallas at all;
                 k sized for ~7 s).  A fault here clears the kernels.

Verdict key: if pipelined/scan-short PASS and scan-long FAULTS regardless
of vmem/kernels, the trigger is sustained single-execution duration (a
worker/tunnel watchdog), and the fix is capping the bench's per-execution
scan length -- serving never runs multi-second executions, so the fault is
a harness artifact, not a serving risk.  Results -> BENCH.md.
"""

from __future__ import annotations

import sys
import time

import numpy as np


def main() -> int:
    phase = sys.argv[1]
    import functools

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    if phase == "scan-long-96m":
        from jax.experimental.pallas import tpu as pltpu

        from kubernetes_deep_learning_tpu.ops import fused_entry, fused_sepconv

        params_cls = (
            getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
        )
        small = lambda: params_cls(vmem_limit_bytes=96 * 1024 * 1024)  # noqa: E731
        fused_sepconv._compiler_params = small
        fused_entry._entry_compiler_params = small

    spec = get_spec("clothing-model")
    dev = jax.devices()[0]
    print(f"[{phase}] device {dev}", flush=True)
    variables = jax.device_put(init_variables(spec, seed=0), dev)
    fast = phase != "scan-long-exact"
    fwd = build_forward(spec, dtype=jnp.bfloat16, fast=True if fast else False)
    fwd_jit = jax.jit(fwd)
    rng = np.random.default_rng(0)
    b = 32
    x = jax.device_put(rng.integers(0, 256, (b, *spec.input_shape), np.uint8), dev)
    jax.block_until_ready(fwd_jit(variables, x))
    t0 = time.perf_counter()
    jax.block_until_ready(fwd_jit(variables, x))
    per = time.perf_counter() - t0
    print(f"[{phase}] warm forward ~{per * 1e3:.1f} ms (incl. RTT)", flush=True)

    @functools.partial(jax.jit, static_argnums=2)
    def chained(v, xi, k):
        def body(carry, _):
            acc, xi = carry
            s = fwd(v, xi).sum()
            bit = jnp.signbit(s).astype(xi.dtype)
            return (acc + s.astype(jnp.float32), xi ^ bit), None

        (acc, _), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), xi), None, length=k
        )
        return acc

    if phase == "pipelined":
        for rep in range(5):
            t0 = time.perf_counter()
            outs = [fwd_jit(variables, x) for _ in range(200)]
            jax.block_until_ready(outs)
            # Materialize one result: forces real completion even if
            # block_until_ready is lazy on this backend, and surfaces any
            # async dispatch error as an exception (= the fault signal
            # this probe exists to catch).
            last = np.asarray(outs[-1])
            assert np.isfinite(last).all()
            dt = (time.perf_counter() - t0) / 200
            print(f"[{phase}] burst {rep}: {dt * 1e3:.2f} ms/iter", flush=True)
    elif phase in ("scan-short", "scan-long", "scan-long-96m", "scan-long-exact"):
        k = 100 if phase == "scan-short" else 900
        calls = 8 if phase == "scan-short" else 3
        t0 = time.perf_counter()
        float(chained(variables, x, k))
        print(f"[{phase}] k={k} compile+first {time.perf_counter() - t0:.1f}s",
              flush=True)
        for rep in range(calls):
            t0 = time.perf_counter()
            float(chained(variables, x, k))
            dt = time.perf_counter() - t0
            print(f"[{phase}] call {rep}: {dt:.2f}s total, "
                  f"{dt / k * 1e3:.2f} ms/iter", flush=True)
    else:
        raise SystemExit(f"unknown phase {phase}")
    print(f"[{phase}] PASS", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
