"""Real per-op attribution: jax.profiler trace of the Xception forward.

Runs the plain jitted forward (batch N) a few times under
jax.profiler.trace, then parses the generated .trace.json.gz and aggregates
device-stream op durations by name prefix -- ground truth for where the
80 ms actually goes (the prefix-delta method double-counts reductions).
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import re
import tempfile
from collections import defaultdict

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--top", type=int, default=25)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec("clothing-model")
    dev = jax.devices()[0]
    variables = jax.device_put(init_variables(spec, seed=0), dev)
    fwd = jax.jit(build_forward(spec, dtype=jnp.bfloat16))
    rng = np.random.default_rng(0)
    x = jax.device_put(
        rng.integers(0, 256, (args.batch, *spec.input_shape), np.uint8), dev
    )
    jax.block_until_ready(fwd(variables, x))  # compile

    trace_dir = tempfile.mkdtemp(prefix="kdlt-prof-")
    with jax.profiler.trace(trace_dir):
        for _ in range(args.iters):
            jax.block_until_ready(fwd(variables, x))

    files = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    assert files, f"no trace files under {trace_dir}"
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)

    # Device-stream complete events: pid whose process_name mentions TPU/XLA
    # ops.  Aggregate wall duration by sanitized op name.
    pids = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids[ev["pid"]] = ev["args"].get("name", "")
    device_pids = {
        pid for pid, name in pids.items() if name.startswith("/device:TPU")
    }
    agg = defaultdict(float)
    count = defaultdict(int)
    details = {}
    total = 0.0
    for ev in trace["traceEvents"]:
        if ev.get("ph") != "X" or ev.get("pid") not in device_pids:
            continue
        name = ev.get("name", "?")
        if name.startswith("jit_"):  # parent span, double-counts children
            continue
        dur = ev.get("dur", 0) / 1e3 / args.iters  # us -> ms, per iter
        agg[name] += dur
        count[name] += 1
        a = ev.get("args") or {}
        details[name] = a.get("long_name") or a.get("hlo_op") or a.get(
            "tf_op"
        ) or ""
        total += dur
    print(f"total device op time/iter: {total:.2f} ms  (batch {args.batch})")
    for key, ms in sorted(agg.items(), key=lambda kv: -kv[1])[: args.top]:
        d = details[key][:110]
        print(f"{ms:9.3f} ms  x{count[key] // args.iters:3d}  {key:28s} {d}")


if __name__ == "__main__":
    main()
