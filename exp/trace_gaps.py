"""Where does wall time go BETWEEN device ops?  Parses a jax.profiler trace
of the forward and reports, per iteration, total span vs sum-of-op-durations
and the largest inter-op gaps -- the 3 ms/iter unexplained by op time at
batch 64 (round 3) is either op-boundary overhead (actionable: fewer, bigger
ops) or a measurement artifact (not actionable)."""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import tempfile

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--top-gaps", type=int, default=12)
    p.add_argument("--entry-kernel", action="store_true")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import init_variables
    from kubernetes_deep_learning_tpu.models.xception_fast import build_fast_forward
    from kubernetes_deep_learning_tpu.modelspec import get_spec
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    spec = get_spec("clothing-model")
    dev = jax.devices()[0]
    variables = jax.device_put(init_variables(spec, seed=0), dev)
    # chunk=False: this probe's op-sum vs bench-p50 comparison is against
    # recorded MONOLITHIC traces; the round-4 serving default would swap in
    # the chunked program at batches 32-64 and shift the op inventory.
    inner = build_fast_forward(
        spec, dtype=jnp.bfloat16, entry_kernel=args.entry_kernel, chunk=False
    )
    fwd = jax.jit(
        lambda v, img: inner(v, normalize(img, spec.preprocessing)).astype(
            jnp.float32
        )
    )
    rng = np.random.default_rng(0)
    x = jax.device_put(
        rng.integers(0, 256, (args.batch, *spec.input_shape), np.uint8), dev
    )
    jax.block_until_ready(fwd(variables, x))

    trace_dir = tempfile.mkdtemp(prefix="kdlt-gaps-")
    with jax.profiler.trace(trace_dir):
        for _ in range(args.iters):
            jax.block_until_ready(fwd(variables, x))

    files = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True)
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)

    events = trace["traceEvents"]
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[e["pid"]] = e["args"].get("name", "")
    dev_pids = [pid for pid, n in names.items() if "TPU" in n or "/device" in n.lower()]
    ops = [
        e
        for e in events
        if e.get("ph") == "X" and e.get("pid") in dev_pids and e.get("dur", 0) > 0
    ]
    print(f"device pids: { {pid: names[pid] for pid in dev_pids} }")
    # Group by thread (device stream), sort by start.
    by_tid: dict = {}
    for e in ops:
        by_tid.setdefault((e["pid"], e["tid"]), []).append(e)
    for key, evs in sorted(by_tid.items(), key=lambda kv: -len(kv[1])):
        evs.sort(key=lambda e: e["ts"])
        span = evs[-1]["ts"] + evs[-1]["dur"] - evs[0]["ts"]
        dur = sum(e["dur"] for e in evs)
        print(
            f"stream {key}: {len(evs)} events, span {span/1e3:.2f} ms, "
            f"busy {dur/1e3:.2f} ms, idle {(span-dur)/1e3:.2f} ms"
        )
        if len(evs) < 10:
            continue
        gaps = []
        for a, b in zip(evs, evs[1:]):
            g = b["ts"] - (a["ts"] + a["dur"])
            if g > 0:
                gaps.append((g, a["name"][:40], b["name"][:40]))
        gaps.sort(reverse=True)
        for g, an, bn in gaps[: args.top_gaps]:
            print(f"   gap {g/1e3:7.3f} ms  after {an!r} -> {bn!r}")


if __name__ == "__main__":
    main()
