"""Does chunking big batches into 16-image microbatches beat the monolith?

Motivation (exp/batch_dip_trace.py): the fused serving path's device time
per image is non-monotonic in batch -- 197 us/img at batch 16 vs 222/232
at 32/48 -- because XLA picks worse fusion schedules for the entry flow at
those sizes.  If a single jitted program that runs batch 32 as
``lax.map`` over 2 chunks of 16 lands near 2 x the batch-16 span, the
engine should serve every bucket >16 as chunked-16 and the whole in-bound
band lifts ~10-15%.

Measures, per batch in --batches: monolithic device span vs chunked device
span (profiler trace totals, RTT-immune), plus logits equivalence.

Usage: python exp/chunked_forward.py --batches 32 48 64 128 [--chunk 16]
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def device_span_ms(fn, args_, iters: int) -> float:
    import jax

    jax.block_until_ready(fn(*args_))  # compile
    trace_dir = tempfile.mkdtemp(prefix="kdlt-chunk-")
    with jax.profiler.trace(trace_dir):
        for _ in range(iters):
            jax.block_until_ready(fn(*args_))
    files = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    assert files, f"no trace files under {trace_dir}"
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)
    pids = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids[ev["pid"]] = ev["args"].get("name", "")
    device_pids = {
        pid for pid, name in pids.items() if name.startswith("/device:TPU")
    }
    total = 0.0
    for ev in trace["traceEvents"]:
        if (
            ev.get("ph") == "X"
            and ev.get("pid") in device_pids
            and not ev.get("name", "").startswith("jit_")
        ):
            total += ev.get("dur", 0) / 1e3
    return total / iters


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batches", type=int, nargs="+", default=[32, 48, 64, 128])
    p.add_argument("--chunk", type=int, default=16)
    p.add_argument("--iters", type=int, default=6)
    p.add_argument(
        "--unrolled",
        action="store_true",
        help="python-loop unroll instead of lax.map (XLA schedules freely)",
    )
    p.add_argument(
        "--model",
        default="clothing-model",
        help="ModelSpec name; non-Xception models measure the plain "
        "build_forward program in both arms (no production chunking exists "
        "for them -- this is the scoping measurement)",
    )
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_deep_learning_tpu.models import (
        build_forward,
        has_fast_forward,
        init_variables,
    )
    from kubernetes_deep_learning_tpu.modelspec import get_spec
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    spec = get_spec(args.model)
    dev = jax.devices()[0]
    variables = jax.device_put(init_variables(spec, seed=0), dev)
    if has_fast_forward(spec):
        from kubernetes_deep_learning_tpu.models.xception_fast import (
            build_fast_forward,
        )

        # chunk=False pins the MONOLITHIC program: since round 4 the serving
        # fast path chunks 32-64 by default (the result of this experiment),
        # so the baseline arm must opt out or both arms measure the same.
        inner = build_fast_forward(spec, dtype=jnp.bfloat16, chunk=False)

        def fwd(v, x):
            return inner(v, normalize(x, spec.preprocessing)).astype(
                jnp.float32
            )

    else:
        fwd = build_forward(spec, dtype=jnp.bfloat16, fast="auto")

    mono = jax.jit(fwd)

    def chunked(v, x):
        k = x.shape[0] // args.chunk
        xs = x.reshape(k, args.chunk, *x.shape[1:])
        return jax.lax.map(lambda c: fwd(v, c), xs).reshape(
            x.shape[0], -1
        )

    def unrolled(v, x):
        # 16-chunks plus an optional trailing 8-chunk, so 8-multiples that
        # are not 16-multiples (40, 56) can chunk too: the batch-8 program
        # is ALSO faster per image (255 us) than the 32-48 monoliths.
        n, c = x.shape[0], args.chunk
        bounds = list(range(0, n - n % c, c))
        if n % c:
            bounds.append(n - n % c)
        outs = [
            fwd(v, x[lo : lo + min(c, n - lo)])
            for lo in bounds
        ]
        return jnp.concatenate(outs, axis=0)

    chk = jax.jit(unrolled if args.unrolled else chunked)

    rng = np.random.default_rng(0)
    print(f"chunk={args.chunk}  (device-span ms/iter via profiler trace)")
    print("batch   mono ms (us/img)   chunked ms (us/img)   chunk/mono")
    for b in args.batches:
        need = 8 if args.unrolled else args.chunk
        if b % need:
            print(f"{b:5d}   skipped (not a multiple of {need})")
            continue
        x = jax.device_put(
            rng.integers(0, 256, (b, *spec.input_shape), np.uint8), dev
        )
        lm = np.asarray(mono(variables, x))
        lc = np.asarray(chk(variables, x))
        rel = float(
            np.max(np.abs(lm - lc) / (np.max(np.abs(lm)) + 1e-9))
        )
        m = device_span_ms(mono, (variables, x), args.iters)
        c = device_span_ms(chk, (variables, x), args.iters)
        print(
            f"{b:5d}   {m:7.2f} ({m / b * 1e3:5.1f})      "
            f"{c:7.2f} ({c / b * 1e3:5.1f})        {c / m:5.2f}x"
            f"   max-rel {rel:.1e}"
        )


if __name__ == "__main__":
    main()
