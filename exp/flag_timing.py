"""Time the full forward under the current XLA_FLAGS (one setting per
process -- XLA reads flags at backend init).  Driven by exp/flag_sweep.sh."""

from __future__ import annotations

import os
import sys
import time
from functools import partial

import numpy as np


def main() -> None:
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec("clothing-model")
    dev = jax.devices()[0]
    variables = jax.device_put(init_variables(spec, seed=0), dev)
    fwd = build_forward(spec, dtype=jnp.bfloat16)

    @partial(jax.jit, static_argnums=2)
    def chained(v, x, k):
        def body(carry, _):
            acc, xi = carry
            s = fwd(v, xi).sum()
            bit = jnp.signbit(s).astype(xi.dtype)
            return (acc + s.astype(jnp.float32), xi ^ bit), None

        (acc, _), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), x), None, length=k
        )
        return acc

    rng = np.random.default_rng(0)
    x = jax.device_put(rng.integers(0, 256, (batch, *spec.input_shape), np.uint8), dev)
    k = 8
    float(chained(variables, x, k))
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(chained(variables, x, k))
        times.append((time.perf_counter() - t0) / k)
    t = float(np.median(times))
    print(
        f"RESULT {t * 1e3:8.3f} ms  {batch / t:8.0f} img/s   "
        f"XLA_FLAGS={os.environ.get('XLA_FLAGS', '')!r}",
        flush=True,
    )


if __name__ == "__main__":
    main()
