"""Pallas prototype: one fused Xception middle block in VMEM.

One grid instance processes ``bt`` images: the (bt,19,19,728) tile stays in
VMEM through relu -> depthwise 3x3 -> pointwise GEMM -> BN affine, three
times, plus the residual add -- eliminating ~7 HBM round trips per block.
Depthwise is 9 shifted multiply-adds on the VPU; pointwise is an MXU GEMM
(bt*361, 728) @ (728, 728) with f32 accumulation.

Validates numerics against the plain-jnp reference, then times:
  asis (XLA graph) vs fused (pallas) at serving batch.
"""

from __future__ import annotations

import argparse
import functools
import time

import numpy as np

C = 728
H = W = 19


def make_refs():
    import jax.numpy as jnp

    def dw_shifted(x, k):
        import jax.numpy as jnp

        xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
        acc = jnp.zeros(x.shape, jnp.float32)
        for i in range(3):
            for j in range(3):
                acc = acc + (
                    xp[:, i : i + x.shape[1], j : j + x.shape[2], :].astype(jnp.float32)
                    * k[i, j].astype(jnp.float32)
                )
        return acc

    def block_ref(x, dw, pw, s, b):
        """Plain-jnp reference of the fused block (bf16 in/out, f32 accum)."""
        y = x
        for i in range(3):
            y = jnp.maximum(y, 0)
            a = dw_shifted(y, dw[i]).astype(jnp.bfloat16)
            z = jnp.einsum(
                "bhwc,cd->bhwd", a, pw[i], preferred_element_type=jnp.float32
            )
            y = (z * s[i] + b[i]).astype(jnp.bfloat16)
        return x + y

    return block_ref


def fused_block_v2(x, dw, pw, s, b, *, bt=4, interpret=False):
    """v2: whole batch as one 2D array, images padded to 368 rows.

    x (B,19,19,C) -> (B*368, C); each grid instance handles bt images =
    (bt*368, C) rows, so the pointwise GEMM has M = bt*368 (MXU-efficient)
    and NOTHING reshapes in-kernel.  Depthwise = 9 shifted FMAs along the
    row dim; validity masks (row/col image edges, 361->368 pad rows) are
    host-precomputed (368,1)-per-image vectors tiled to the block.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B = x.shape[0]
    HW, HWp = H * W, 368  # padded rows per image (multiple of 8 sublanes)
    bt = min(bt, B)
    assert B % bt == 0, (B, bt)
    T = bt * HWp

    x2 = jnp.pad(x.reshape(B, HW, C), ((0, 0), (0, HWp - HW), (0, 0)))
    x2 = x2.reshape(B * HWp, C)

    # Host-side masks, one image period, tiled to the block size.
    r = np.arange(HWp)
    h_idx, w_idx = r // W, r % W
    valid = (r < HW).astype(np.float32)
    base = {
        "valid": valid,
        "row0": valid * (h_idx != 0),        # dh=-1 targets need h>0
        "row18": valid * (h_idx != H - 1),   # dh=+1 targets need h<18
        "col0": valid * (w_idx != 0),
        "col18": valid * (w_idx != W - 1),
    }

    def tiled(v):
        return jnp.asarray(np.tile(v, bt)[:, None])

    def tap_mask(dh, dwc):
        m = base["valid"].copy()
        if dh == -1:
            m = m * base["row0"]
        elif dh == 1:
            m = m * base["row18"]
        if dwc == -1:
            m = m * base["col0"]
        elif dwc == 1:
            m = m * base["col18"]
        return m

    taps = [(dh, dwc) for dh in (-1, 0, 1) for dwc in (-1, 0, 1)]
    masks = jnp.concatenate(
        [tiled(tap_mask(dh, dwc)) for dh, dwc in taps], axis=1
    )  # (T, 9)
    mvalid = tiled(base["valid"])  # (T, 1)

    PAD = W + 1  # covers the largest |offset|

    def kernel(x_ref, dw_ref, pw_ref, s_ref, b_ref, mk_ref, mv_ref, o_ref):
        y = x_ref[...]  # (T, C) bf16
        res = y
        for i in range(3):
            y = jnp.maximum(y, 0)
            # bf16 pad buffer (halves VMEM); products accumulate in f32.
            yp = jnp.pad(y, ((PAD, PAD), (0, 0)))
            acc = jnp.zeros((T, C), jnp.float32)
            for t, (dh, dwc) in enumerate(taps):
                o = W * dh + dwc  # row stride is W within an image
                tap = dw_ref[i, dh + 1, dwc + 1, :].astype(jnp.float32)
                contrib = yp[PAD + o : PAD + o + T, :].astype(jnp.float32) * tap
                acc = acc + contrib * mk_ref[:, t : t + 1]
            z = jax.lax.dot_general(
                acc.astype(jnp.bfloat16),
                pw_ref[i],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            y = ((z * s_ref[i] + b_ref[i]) * mv_ref[...]).astype(jnp.bfloat16)
        o_ref[...] = res + y

    try:
        from jax.experimental.pallas import tpu as pltpu

        compiler_params = pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        )
    except Exception:  # older API name
        from jax.experimental.pallas import tpu as pltpu

        compiler_params = pltpu.TPUCompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        )

    out = pl.pallas_call(
        kernel,
        grid=(B // bt,),
        in_specs=[
            pl.BlockSpec((T, C), lambda g: (g, 0)),
            pl.BlockSpec((3, 3, 3, C), lambda g: (0, 0, 0, 0)),
            pl.BlockSpec((3, C, C), lambda g: (0, 0, 0)),
            pl.BlockSpec((3, C), lambda g: (0, 0)),
            pl.BlockSpec((3, C), lambda g: (0, 0)),
            pl.BlockSpec((T, 9), lambda g: (0, 0)),
            pl.BlockSpec((T, 1), lambda g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((T, C), lambda g: (g, 0)),
        out_shape=jax.ShapeDtypeStruct((B * HWp, C), x.dtype),
        compiler_params=compiler_params,
        interpret=interpret,
    )(x2, dw, pw, s, b, masks, mvalid)
    return out.reshape(B, HWp, C)[:, :HW, :].reshape(B, H, W, C)


def fused_block_v3(xt, dw, pw, s, b, *, bt=8, interpret=False):
    """v3: (H, W, B, C) layout -- batch on sublanes, channels on lanes.

    Depthwise shifts become OUTER-dim slices (no sublane/lane relayout at
    all, the v1/v2 killer); the whole 19x19 spatial extent of ``bt`` images
    sits in one VMEM tile, so zero-padding h/w gives exact SAME-conv halos
    with no masks; the pointwise GEMM collapses (19,19,bt) -> M rows over a
    full (bt sublane, C lane) tile, which Mosaic reshapes for free.

    Takes and returns the TRANSPOSED activation (H, W, B, C): chained middle
    blocks stay in this layout, paying the NHWC transpose once at entry and
    once at exit of the whole middle flow.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Hh, Ww, B, Cc = xt.shape
    assert (Hh, Ww, Cc) == (H, W, C)
    bt = min(bt, B)
    assert B % bt == 0

    def kernel(x_ref, dw_ref, pw_ref, s_ref, b_ref, o_ref):
        y = x_ref[...]  # (H, W, bt, C) bf16
        for i in range(3):
            y = jnp.maximum(y, 0)
            yp = jnp.pad(y, ((1, 1), (1, 1), (0, 0), (0, 0)))
            acc = jnp.zeros((H, W, bt, C), jnp.float32)
            for dh in range(3):
                for dwc in range(3):
                    tap = dw_ref[i, dh, dwc, :].astype(jnp.float32)
                    acc = acc + (
                        yp[dh : dh + H, dwc : dwc + W, :, :].astype(jnp.float32)
                        * tap
                    )
            z = jax.lax.dot_general(
                acc.astype(jnp.bfloat16).reshape(H * W * bt, C),
                pw_ref[i],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            y = (
                (z * s_ref[i] + b_ref[i])
                .astype(jnp.bfloat16)
                .reshape(H, W, bt, C)
            )
        o_ref[...] = x_ref[...] + y

    return pl.pallas_call(
        kernel,
        grid=(B // bt,),
        in_specs=[
            pl.BlockSpec((H, W, bt, C), lambda g: (0, 0, g, 0)),
            pl.BlockSpec((3, 3, 3, C), lambda g: (0, 0, 0, 0)),
            pl.BlockSpec((3, C, C), lambda g: (0, 0, 0)),
            pl.BlockSpec((3, C), lambda g: (0, 0)),
            pl.BlockSpec((3, C), lambda g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((H, W, bt, C), lambda g: (0, 0, g, 0)),
        out_shape=jax.ShapeDtypeStruct(xt.shape, xt.dtype),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        ),
        interpret=interpret,
    )(xt, dw, pw, s, b)


def fused_block_v3_nhwc(x, dw, pw, s, b, *, bt=8, interpret=False):
    """NHWC wrapper for the numeric check / standalone timing: transpose in,
    run v3, transpose out (chained use pays the transposes once per flow)."""
    xt = x.transpose(1, 2, 0, 3)
    out = fused_block_v3(xt, dw, pw, s, b, bt=bt, interpret=interpret)
    return out.transpose(2, 0, 1, 3)


def fused_block(x, dw, pw, s, b, *, bt=1, interpret=False):
    """x (B,19,19,728) bf16; dw (3,3,3,C) f32; pw (3,C,C) bf16; s,b (3,C) f32.

    Kernel layout: spatial is flattened OUTSIDE the kernel to (B, 361, C) --
    Mosaic cannot shape-cast (19,19) sublanes in-kernel.  The depthwise conv
    becomes 9 statically-shifted multiply-adds along the flattened dim
    (row shift = +-19, col shift = +-1) with column-edge masks passed in as
    (361, 1) constants (a col shift crosses image rows at w=0/18; row
    overflow lands outside the padded range and is zero).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B = x.shape[0]
    HW = H * W
    x2 = x.reshape(B, HW, C)

    # Column-edge validity masks, by col-shift direction (target-side).
    w_idx = np.arange(HW) % W
    m_m1 = jnp.asarray((w_idx != 0).astype(np.float32)[:, None])    # dwc=-1
    m_p1 = jnp.asarray((w_idx != W - 1).astype(np.float32)[:, None])  # dwc=+1

    def kernel(x_ref, dw_ref, pw_ref, s_ref, b_ref, mm_ref, mp_ref, o_ref):
        y = x_ref[0]  # (361, C) bf16
        res = y
        for i in range(3):
            y = jnp.maximum(y, 0)
            yp = jnp.pad(
                y.astype(jnp.float32), ((W + 1, W + 1), (0, 0))
            )  # (361 + 40, C)
            acc = jnp.zeros((HW, C), jnp.float32)
            for dh in (-1, 0, 1):
                for dwc in (-1, 0, 1):
                    o = W * dh + dwc
                    tap = dw_ref[i, dh + 1, dwc + 1, :].astype(jnp.float32)
                    contrib = yp[W + 1 + o : W + 1 + o + HW, :] * tap
                    if dwc == -1:
                        contrib = contrib * mm_ref[...]
                    elif dwc == 1:
                        contrib = contrib * mp_ref[...]
                    acc = acc + contrib
            z = jax.lax.dot_general(
                acc.astype(jnp.bfloat16),
                pw_ref[i],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            y = (z * s_ref[i] + b_ref[i]).astype(jnp.bfloat16)
        o_ref[0] = res + y

    out = pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, HW, C), lambda g: (g, 0, 0)),
            pl.BlockSpec((3, 3, 3, C), lambda g: (0, 0, 0, 0)),
            pl.BlockSpec((3, C, C), lambda g: (0, 0, 0)),
            pl.BlockSpec((3, C), lambda g: (0, 0)),
            pl.BlockSpec((3, C), lambda g: (0, 0)),
            pl.BlockSpec((HW, 1), lambda g: (0, 0)),
            pl.BlockSpec((HW, 1), lambda g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, HW, C), lambda g: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, HW, C), x.dtype),
        interpret=interpret,
    )(x2, dw, pw, s, b, m_m1, m_p1)
    return out.reshape(B, H, W, C)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--bt", type=int, default=4)
    p.add_argument("--scan-len", type=int, default=16)
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--interpret", action="store_true")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"device: {dev}, batch {args.batch}, bt {args.bt}")
    rng = np.random.default_rng(0)
    dw = jnp.asarray(rng.normal(0, 0.2, (3, 3, 3, C)), jnp.float32)
    pw = jnp.asarray(rng.normal(0, 0.03, (3, C, C)), jnp.bfloat16)
    s = jnp.asarray(rng.uniform(0.8, 1.2, (3, C)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 0.1, (3, C)), jnp.float32)

    block_ref = make_refs()
    x_small = jnp.asarray(rng.normal(0, 1, (4, H, W, C)), jnp.bfloat16)
    want = np.asarray(jax.jit(block_ref)(x_small, dw, pw, s, b), np.float32)
    for vname, vfn in (
        ("fused", fused_block),
        ("fused_v2", fused_block_v2),
        ("fused_v3", fused_block_v3_nhwc),
    ):
        got = np.asarray(
            jax.jit(functools.partial(vfn, bt=4, interpret=args.interpret))(
                x_small, dw, pw, s, b
            ),
            np.float32,
        )
        rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
        print(f"{vname} vs ref max rel err: {rel:.2e}")
        assert rel < 3e-2, f"{vname} diverges"
    if args.interpret:
        print("interpret-mode check PASS")
        return

    x = jax.device_put(jnp.asarray(rng.normal(0, 1, (args.batch, H, W, C)), jnp.bfloat16), dev)
    gemm_tf = 3 * args.batch * H * W * C * C * 2 / 1e12

    for name, fn in (
        ("asis", block_ref),
        ("fused_v3_bt8", functools.partial(fused_block_v3_nhwc, bt=8)),
        ("fused_v3_bt16", functools.partial(fused_block_v3_nhwc, bt=16)),
        ("fused_v3_bt4", functools.partial(fused_block_v3_nhwc, bt=4)),
    ):
        @functools.partial(jax.jit, static_argnums=6)
        def chained(xx, dw, pw, s, b, _unused, k, fn=fn):
            def body(carry, _):
                acc, xi = carry
                out = fn(xi, dw, pw, s, b)
                ss = out.sum()
                xi = xi + (jnp.sign(ss) * 1e-3).astype(xi.dtype)
                return (acc + ss.astype(jnp.float32), xi), None

            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), xx), None, length=k
            )
            return acc

        try:
            float(chained(x, dw, pw, s, b, None, args.scan_len))
            times = []
            for _ in range(args.reps):
                t0 = time.perf_counter()
                float(chained(x, dw, pw, s, b, None, args.scan_len))
                times.append((time.perf_counter() - t0) / args.scan_len)
            t = float(np.median(times))
            print(
                f"{name:12s}: {t * 1e3:8.3f} ms  GEMM-only MFU {gemm_tf / t / 197 * 100:4.1f}%"
            )
        except Exception as e:
            print(f"{name:12s}: FAILED {str(e).splitlines()[0][:120]}")


if __name__ == "__main__":
    main()
