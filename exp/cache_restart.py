#!/usr/bin/env python
"""Measure serving cold-start vs cache-warm restart on the real chip.

VERDICT r4 weak-5: the mitigation stack for multi-minute XLA warmup
(parallel compiles, chunked-bucket startupProbe budgets) treats the
symptom; the reference's TF-Serving pod boots and serves immediately
(/root/reference/tf-serving.dockerfile:1-5) while a v5e pod eviction here
costs ~10 minutes of cold compile.  The fix is a persistent compilation
cache (utils/compilecache.py) on a volume that outlives the container
(deploy/k8s/model-server-deployment.yaml's xla-cache emptyDir).

This harness quantifies exactly that: two FRESH processes run the real
InferenceEngine warmup over the serving bucket ladder against the same
cache directory -- the first cold (populating it), the second simulating
the restarted pod (reading it).  The ratio is the record.

Usage:
    python exp/cache_restart.py                      # full serving ladder
    python exp/cache_restart.py --buckets 1,8,16     # quicker probe
    python exp/cache_restart.py --out exp/records/r05_cache_restart.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def child(cache_dir: str, model: str, buckets: tuple[int, ...]) -> None:
    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.models import init_variables
    from kubernetes_deep_learning_tpu.modelspec import get_spec
    from kubernetes_deep_learning_tpu.runtime.engine import InferenceEngine
    from kubernetes_deep_learning_tpu.utils.compilecache import enable_compile_cache

    assert enable_compile_cache(cache_dir=cache_dir), "cache must enable"
    spec = get_spec(model)
    root = tempfile.mkdtemp(prefix="kdlt-cache-restart-")
    art.save_artifact(
        art.version_dir(root, spec.name, 1), spec,
        init_variables(spec, seed=0), None, {"compute_dtype": "bfloat16"},
    )
    artifact = art.load_artifact(art.version_dir(root, spec.name, 1))
    engine = InferenceEngine(artifact, buckets=buckets)
    t0 = time.perf_counter()
    warm_s = engine.warmup()
    wall_s = time.perf_counter() - t0
    print(json.dumps({
        "warmup_s": round(warm_s, 2),
        "wall_s": round(wall_s, 2),
        "fast_degraded": engine.fast_degraded,
    }), flush=True)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="clothing-model")
    p.add_argument("--buckets", default="1,2,4,8,16,32,64,128",
                   help="the k8s model server's default ladder")
    p.add_argument("--cache-dir", default="",
                   help="cache directory (default: fresh temp dir, removed "
                        "after; pass a path to inspect entries)")
    p.add_argument("--out", default="",
                   help="write the record JSON here as well as stdout")
    p.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = p.parse_args()
    buckets = tuple(int(b) for b in args.buckets.split(","))

    if args.child:
        child(args.cache_dir, args.model, buckets)
        return 0

    # Only a temp dir WE created is ever wiped: an operator-supplied
    # --cache-dir may be a live production cache (e.g. .jax_cache), and the
    # cold/restart split simply reads differently on a pre-populated dir
    # (the "cold" row is then already partially warm -- noted in stderr).
    cleanup = not args.cache_dir
    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="kdlt-cache-exp-")
    if cleanup:
        shutil.rmtree(cache_dir, ignore_errors=True)
    elif os.path.isdir(cache_dir) and os.listdir(cache_dir):
        print(
            f"note: {cache_dir} is non-empty; the 'cold' row will read "
            "partially warm (pass no --cache-dir for a true cold run)",
            file=sys.stderr,
        )
    os.makedirs(cache_dir, exist_ok=True)
    runs = {}
    try:
        for label in ("cold", "restart"):
            cmd = [
                sys.executable, os.path.abspath(__file__), "--child",
                "--model", args.model, "--buckets", args.buckets,
                "--cache-dir", cache_dir,
            ]
            t0 = time.perf_counter()
            r = subprocess.run(cmd, stdout=subprocess.PIPE, timeout=3600)
            wall = time.perf_counter() - t0
            if r.returncode != 0:
                print(f"{label}: child failed rc={r.returncode}", file=sys.stderr)
                return 1
            row = json.loads(r.stdout.decode().strip().splitlines()[-1])
            row["process_wall_s"] = round(wall, 2)
            runs[label] = row
            n_entries = sum(
                len(fs) for _, _, fs in os.walk(cache_dir)
            )
            print(
                f"{label}: warmup {row['warmup_s']}s (process wall "
                f"{row['process_wall_s']}s), cache entries now {n_entries}",
                file=sys.stderr,
            )
            runs[label]["cache_entries_after"] = n_entries
    finally:
        if cleanup:
            shutil.rmtree(cache_dir, ignore_errors=True)

    speedup = runs["cold"]["warmup_s"] / max(runs["restart"]["warmup_s"], 1e-9)
    out = {
        "metric": (
            f"{args.model} warmup seconds over buckets ({args.buckets}): "
            "cold vs cache-warm restart (persistent XLA compilation cache, "
            "fresh process each; the restart row is what a k8s container "
            "restart pays with the xla-cache volume mounted)"
        ),
        "cold_warmup_s": runs["cold"]["warmup_s"],
        "restart_warmup_s": runs["restart"]["warmup_s"],
        "speedup": round(speedup, 1),
        "runs": runs,
    }
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
