"""Scoping: is the MXU's int8 path worth an in-kernel activation-quant
pass at the fused sepconv GEMM shapes?  Times XLA-level GEMM chains
(anti-LICM chained scan) for bf16 vs int8x int8->int32, at the middle-flow
pointwise shapes for serving-relevant batches."""

from __future__ import annotations

import functools
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"device: {dev}")
    rng = np.random.default_rng(0)
    C = 728
    for bt in (1, 8, 16, 64):
        M = 19 * 19 * bt
        results = {}
        for name, dtype, pref in (
            ("bf16", jnp.bfloat16, jnp.float32),
            ("int8", jnp.int8, jnp.int32),
        ):
            if name == "int8":
                a = jnp.asarray(rng.integers(-127, 127, (M, C)), jnp.int8)
                w = jnp.asarray(rng.integers(-127, 127, (C, C)), jnp.int8)
            else:
                a = jnp.asarray(rng.normal(0, 1, (M, C)), dtype)
                w = jnp.asarray(rng.normal(0, 1, (C, C)), dtype)

            @functools.partial(jax.jit, static_argnums=2)
            def chained(a0, w, k):
                def body(carry, _):
                    acc = jax.lax.dot_general(
                        carry, w, (((1,), (0,)), ((), ())),
                        preferred_element_type=pref,
                    )
                    # data-dependent feedback, cast back to operand dtype
                    nxt = acc.astype(a0.dtype) if name == "bf16" else (
                        (acc >> 7).astype(jnp.int8)
                    )
                    return nxt, None

                out, _ = jax.lax.scan(body, a0, None, length=k)
                # fold to a scalar the caller prints: the full (M, C) carry
                # is returned through the tunnel otherwise (slow), and a
                # consumed scalar also guards against output elision.
                return out.astype(jnp.int32).sum() if name == "int8" else out.sum()

            # long calls: wall >=0.5 s so the per-call RTT is noise, and
            # achieved rate must stay under the physical peak or the run is
            # rejected (the first version of this harness reported 9.6
            # PFLOP/s -- scan output elision).
            k = max(2000, int(0.5 / (2 * M * C * C / 197e12)))
            float(chained(a, w, k))
            times = []
            for _ in range(4):
                t0 = time.perf_counter()
                float(chained(a, w, k))
                times.append((time.perf_counter() - t0) / k)
            us = float(np.median(times)) * 1e6
            flops = 2 * M * C * C
            tflops = flops / (us * 1e-6) / 1e12  # FLOP / s -> TFLOP/s
            peak = 394.0 if name == "int8" else 197.0  # TFLOP/s (TOPS), v5e
            flag = "  IMPOSSIBLE(>peak)" if tflops > peak else ""
            results[name] = us
            print(f"  bt={bt:3d} {name}: {us:8.2f} us/GEMM "
                  f"({tflops:6.1f} TFLOP/s, {tflops/peak*100:5.1f}% peak){flag}")
        print(f"  bt={bt:3d} int8 speedup: {results['bf16']/results['int8']:.2f}x")


if __name__ == "__main__":
    main()
